"""GPU shared-device bookkeeping.

Mirrors /root/reference/pkg/scheduler/api/device_info.go:23-72 and the
node-side wiring in node_info.go:268-291,460-480: each node exposes a set of
GPU cards with per-card memory; GPU-sharing tasks request
``volcano.sh/gpu-memory`` and are packed onto single cards.

TPU-first note: besides the per-object accounting used by the callback
predicate path, :func:`devices_idle_matrix` flattens the per-node card state
into a dense ``f32[N, D]`` matrix so the GPU-share feasibility test (max over
cards of idle memory >= request) is one vectorised reduction inside the
device solve.
"""

from __future__ import annotations

from typing import Dict, Optional

# volcano.sh/gpu-memory — per-card memory requested by a sharing task
# (well_known_labels.go:22); volcano.sh/gpu-number — number of physical cards
# on a node (well_known_labels.go:25).
GPU_MEMORY_RESOURCE = "volcano.sh/gpu-memory"
GPU_NUMBER_RESOURCE = "volcano.sh/gpu-number"
GPU_INDEX_ANNOTATION = "volcano.sh/gpu-index"
GPU_ASSIGNED_ANNOTATION = "volcano.sh/gpu-assigned"


def gpu_memory_of_task(task) -> float:
    """GPU memory requested by a task (device_info.go GetGPUResourceOfPod).
    Returned in the Resource scalar space (milli-scaled when built via
    Resource.from_dict); GPUDevice.memory lives in the same space because
    NodeInfo wires it from the capacity scalar unchanged."""
    return float(task.resreq.get(GPU_MEMORY_RESOURCE))


class GPUDevice:
    """One GPU card: id, per-card memory, and the tasks sharing it
    (device_info.go:23-40)."""

    def __init__(self, id: int, memory: float):
        self.id = id
        self.memory = memory
        # task uid -> requested gpu memory on this card
        self.task_map: Dict[str, float] = {}

    def used_memory(self) -> float:
        """device_info.go getUsedGPUMemory (terminated pods excluded at
        add/sub time by the node accounting)."""
        return sum(self.task_map.values())

    def idle_memory(self) -> float:
        return self.memory - self.used_memory()

    def clone(self) -> "GPUDevice":
        d = GPUDevice(self.id, self.memory)
        d.task_map = dict(self.task_map)
        return d


def make_gpu_devices(total_memory: float, card_count: int) -> Dict[int, GPUDevice]:
    """node_info.go setNodeGPUInfo:268-291 — split node GPU capacity into
    per-card devices of equal memory."""
    if card_count <= 0:
        return {}
    per_card = total_memory / card_count
    return {i: GPUDevice(i, per_card) for i in range(card_count)}


def predicate_gpu(task, devices: Dict[int, GPUDevice]) -> Optional[int]:
    """First card with enough idle memory for the request, lowest id first
    (predicates/gpu.go predicateGPU); None if no card fits."""
    request = gpu_memory_of_task(task)
    for dev_id in sorted(devices):
        if devices[dev_id].idle_memory() >= request:
            return dev_id
    return None


def add_gpu_resource(devices: Dict[int, GPUDevice], task) -> Optional[int]:
    """Account a placed GPU-sharing task onto its card (node_info.go
    AddGPUResource). The card comes from the task's gpu-index annotation if
    present, else the first fitting card."""
    request = gpu_memory_of_task(task)
    if request <= 0 or not devices:
        return None
    index = task.annotations.get(GPU_INDEX_ANNOTATION)
    dev_id = None
    if index is not None:
        try:
            dev_id = int(index)
        except ValueError:
            # invalid annotation: log-and-skip in the reference
            # (pod_info.go GetGPUIndex:141-155); fall back to first fit
            dev_id = None
    if dev_id is None:
        dev_id = predicate_gpu(task, devices)
    if dev_id is None or dev_id not in devices:
        return None
    devices[dev_id].task_map[task.uid] = request
    return dev_id


def sub_gpu_resource(devices: Dict[int, GPUDevice], task) -> None:
    """node_info.go SubGPUResource."""
    for device in devices.values():
        device.task_map.pop(task.uid, None)


def devices_idle_gpu_memory(devices: Dict[int, GPUDevice]) -> Dict[int, float]:
    """node_info.go GetDevicesIdleGPUMemory."""
    return {dev_id: dev.idle_memory() for dev_id, dev in devices.items()}


def devices_idle_matrix(nodes, max_cards: Optional[int] = None):
    """Dense ``f32[N, D]`` idle-GPU-memory matrix over a node list, padded
    with -inf for absent cards — the tensor-path feed for the GPU-sharing
    feasibility mask (feasible iff ``max_d idle[n, d] >= request``)."""
    import numpy as np

    if max_cards is None:
        max_cards = max((len(n.gpu_devices) for n in nodes), default=0)
    out = np.full((len(nodes), max(max_cards, 1)), -np.inf, dtype=np.float32)
    for i, node in enumerate(nodes):
        for dev_id, dev in node.gpu_devices.items():
            if dev_id < max_cards:
                out[i, dev_id] = dev.idle_memory()
    return out
