"""ClusterInfo: the per-cycle snapshot root.

Mirrors /root/reference/pkg/scheduler/api/cluster_info.go. The snapshot is the
session's isolated world: plugins and actions mutate only this copy, never the
live cache. The TPU path additionally materializes it into dense tensors
(see volcano_tpu.cache.snapshot.SnapshotTensors).
"""

from __future__ import annotations

from typing import Dict

from .job_info import JobInfo
from .node_info import NodeInfo
from .queue_info import NamespaceInfo, QueueInfo


class ClusterInfo:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespaces: Dict[str, NamespaceInfo] = {}
        self.revocable_nodes: Dict[str, NodeInfo] = {}
        self.node_list: list = []

    def __repr__(self) -> str:
        return (f"ClusterInfo(jobs={len(self.jobs)} nodes={len(self.nodes)} "
                f"queues={len(self.queues)})")
