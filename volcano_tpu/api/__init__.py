"""Scheduler in-memory model (the reference's pkg/scheduler/api, re-shaped
for dense-tensor snapshots)."""

from .resource import (CPU, GPU_RESOURCE_NAME, INFINITY, MEMORY, MIN_RESOURCE,
                       PODS, TPU_RESOURCE_NAME, ZERO, Resource, ResourceNames,
                       parse_quantity)
from .types import (BusAction, BusEvent, JobPhase, NodePhase, PodGroupPhase,
                    PodGroupConditionType, QueueState, TaskStatus,
                    allocated_status)
from .job_info import DisruptionBudget, JobInfo, PodGroup, TaskInfo
from .node_info import NodeInfo
from .queue_info import NamespaceCollection, NamespaceInfo, QueueInfo, QueueSpec
from .cluster_info import ClusterInfo
from .unschedule_info import FitError, FitErrors

__all__ = [
    "CPU", "GPU_RESOURCE_NAME", "INFINITY", "MEMORY", "MIN_RESOURCE", "PODS",
    "TPU_RESOURCE_NAME", "ZERO", "Resource", "ResourceNames", "parse_quantity",
    "BusAction", "BusEvent", "JobPhase", "NodePhase", "PodGroupPhase",
    "PodGroupConditionType", "QueueState", "TaskStatus", "allocated_status",
    "DisruptionBudget", "JobInfo", "PodGroup", "TaskInfo", "NodeInfo",
    "NamespaceCollection", "NamespaceInfo", "QueueInfo", "QueueSpec",
    "ClusterInfo", "FitError", "FitErrors",
]
