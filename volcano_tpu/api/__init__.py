"""Scheduler in-memory model (the reference's pkg/scheduler/api, re-shaped
for dense-tensor snapshots)."""

from .resource import (CPU, GPU_RESOURCE_NAME, INFINITY, MEMORY, MIN_RESOURCE,
                       PODS, TPU_RESOURCE_NAME, ZERO, Resource, ResourceNames,
                       parse_quantity)
from .types import (BusAction, BusEvent, JobPhase, NodePhase, PodGroupPhase,
                    PodGroupConditionType, QueueState, TaskStatus,
                    allocated_status)
from .job_info import DisruptionBudget, JobInfo, PodGroup, TaskInfo
from .node_info import NodeInfo
from .queue_info import NamespaceCollection, NamespaceInfo, QueueInfo, QueueSpec
from .cluster_info import ClusterInfo
from .unschedule_info import FitError, FitErrors
from .device_info import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE, GPUDevice,
                          devices_idle_gpu_memory, gpu_memory_of_task,
                          make_gpu_devices, predicate_gpu)
from .numa_info import (CPUInfo, NumatopoInfo, ResNumaSets, ResourceInfo,
                        TopologyHint, generate_node_res_numa_sets,
                        generate_numa_nodes, get_policy)

__all__ = [
    "CPU", "GPU_RESOURCE_NAME", "INFINITY", "MEMORY", "MIN_RESOURCE", "PODS",
    "TPU_RESOURCE_NAME", "ZERO", "Resource", "ResourceNames", "parse_quantity",
    "BusAction", "BusEvent", "JobPhase", "NodePhase", "PodGroupPhase",
    "PodGroupConditionType", "QueueState", "TaskStatus", "allocated_status",
    "DisruptionBudget", "JobInfo", "PodGroup", "TaskInfo", "NodeInfo",
    "NamespaceCollection", "NamespaceInfo", "QueueInfo", "QueueSpec",
    "ClusterInfo", "FitError", "FitErrors",
    "GPU_MEMORY_RESOURCE", "GPU_NUMBER_RESOURCE", "GPUDevice",
    "devices_idle_gpu_memory", "gpu_memory_of_task", "make_gpu_devices",
    "predicate_gpu",
    "CPUInfo", "NumatopoInfo", "ResNumaSets", "ResourceInfo", "TopologyHint",
    "generate_node_res_numa_sets", "generate_numa_nodes", "get_policy",
]
