"""QueueInfo and NamespaceInfo.

Mirrors /root/reference/pkg/scheduler/api/queue_info.go and
namespace_info.go:1-145.
"""

from __future__ import annotations

from typing import Dict, Optional

from .resource import Resource
from .types import QueueState

DEFAULT_NAMESPACE_WEIGHT = 1

# scheduling/v1beta1 annotation keys (vendor/volcano.sh/apis labels.go:19-21)
KUBE_HIERARCHY_ANNOTATION_KEY = "volcano.sh/hierarchy"
KUBE_HIERARCHY_WEIGHT_ANNOTATION_KEY = "volcano.sh/hierarchy-weights"


class QueueSpec:
    """scheduling/v1beta1 Queue spec mirror."""

    def __init__(self, name: str = "default", weight: int = 1,
                 capability: Optional[Resource] = None,
                 reclaimable: bool = True,
                 state: QueueState = QueueState.OPEN,
                 annotations: Optional[Dict[str, str]] = None):
        self.name = name
        self.weight = weight
        self.capability = capability
        self.reclaimable = reclaimable
        self.state = state
        self.annotations = dict(annotations or {})


class QueueInfo:
    def __init__(self, uid: str = "", name: str = "", weight: int = 1,
                 capability: Optional[Resource] = None,
                 reclaimable: bool = True,
                 state: QueueState = QueueState.OPEN,
                 annotations: Optional[Dict[str, str]] = None):
        self.uid = uid or name
        self.name = name or self.uid
        self.weight = weight
        self.capability = capability      # None => unlimited in every dimension
        self.reclaimable = reclaimable
        self.state = state
        self.annotations = dict(annotations or {})

    @property
    def hierarchy(self) -> str:
        """Slash-separated path in the queue tree (queue_info.go:40-55)."""
        return self.annotations.get(KUBE_HIERARCHY_ANNOTATION_KEY, "")

    @property
    def hierarchy_weights(self) -> str:
        return self.annotations.get(KUBE_HIERARCHY_WEIGHT_ANNOTATION_KEY, "")

    @classmethod
    def from_spec(cls, spec: QueueSpec) -> "QueueInfo":
        return cls(uid=spec.name, name=spec.name, weight=spec.weight,
                   capability=spec.capability, reclaimable=spec.reclaimable,
                   state=spec.state, annotations=spec.annotations)

    def clone(self) -> "QueueInfo":
        return QueueInfo(uid=self.uid, name=self.name, weight=self.weight,
                         capability=self.capability, reclaimable=self.reclaimable,
                         state=self.state, annotations=self.annotations)

    def __repr__(self) -> str:
        return f"Queue({self.name} weight={self.weight})"


class NamespaceInfo:
    def __init__(self, name: str, weight: int = DEFAULT_NAMESPACE_WEIGHT):
        self.name = name
        self.weight = weight

    def get_weight(self) -> int:
        return self.weight if self.weight > 0 else DEFAULT_NAMESPACE_WEIGHT


class NamespaceCollection:
    """Tracks namespace weights from quota-style annotations
    (namespace_info.go:60-145)."""

    WEIGHT_KEY = "volcano.sh/namespace.weight"

    def __init__(self, name: str):
        self.name = name
        self._weights: Dict[str, int] = {}

    def update(self, source: str, weight: int) -> None:
        self._weights[source] = weight

    def delete(self, source: str) -> None:
        self._weights.pop(source, None)

    def snapshot(self) -> NamespaceInfo:
        weight = max(self._weights.values()) if self._weights else DEFAULT_NAMESPACE_WEIGHT
        return NamespaceInfo(self.name, weight)
