"""Fit errors: why a task failed to place on nodes.

Mirrors /root/reference/pkg/scheduler/api/unschedule_info.go:1-101.
"""

from __future__ import annotations

from typing import Dict, List


class FitError:
    def __init__(self, task=None, node=None, reasons: List[str] = ()):
        self.task_name = getattr(task, "name", "")
        self.task_namespace = getattr(task, "namespace", "")
        self.node_name = getattr(node, "name", "")
        self.reasons = list(reasons)

    def error(self) -> str:
        return (f"task {self.task_namespace}/{self.task_name} on node "
                f"{self.node_name} fit failed: {', '.join(self.reasons)}")

    def __repr__(self) -> str:
        return self.error()


class FitErrors:
    """Aggregates per-node FitError for one task, with reason histogram."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self.err: str = ""

    def set_node_error(self, node_name: str, err: object) -> None:
        if isinstance(err, FitError):
            fe = err
        else:
            fe = FitError(reasons=[str(err)])
            fe.node_name = node_name
        self.nodes[node_name] = fe

    def set_error(self, err: str) -> None:
        self.err = err

    def error(self) -> str:
        if self.err:
            return self.err
        reasons: Dict[str, int] = {}
        for fe in self.nodes.values():
            for r in fe.reasons:
                reasons[r] = reasons.get(r, 0) + 1
        sorted_reasons = sorted(reasons.items(), key=lambda kv: (-kv[1], kv[0]))
        return "all nodes are unavailable: " + ", ".join(
            f"{n} {r}" for r, n in sorted_reasons) + "."
