"""Core enums and callback-type conventions.

Mirrors /root/reference/pkg/scheduler/api/types.go:23-167 and the CRD phase
enums from vendor/volcano.sh/apis (scheduling/v1beta1/types.go, bus/v1alpha1).
"""

from __future__ import annotations

import enum


class TaskStatus(enum.IntEnum):
    """Task lifecycle status (types.go:23-58)."""

    PENDING = 1
    ALLOCATED = 2
    PIPELINED = 3
    BINDING = 4
    BOUND = 5
    RUNNING = 6
    RELEASING = 7
    SUCCEEDED = 8
    FAILED = 9
    UNKNOWN = 10


def allocated_status(status: TaskStatus) -> bool:
    """AllocatedStatus (types.go:75-84): statuses that occupy node resources."""
    return status in (TaskStatus.BOUND, TaskStatus.BINDING,
                      TaskStatus.RUNNING, TaskStatus.ALLOCATED)


class PodGroupPhase(str, enum.Enum):
    """scheduling/v1beta1 PodGroupPhase (vendor .../scheduling/v1beta1/types.go)."""

    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


class PodGroupConditionType(str, enum.Enum):
    SCHEDULED = "Scheduled"
    UNSCHEDULABLE = "Unschedulable"


class QueueState(str, enum.Enum):
    """scheduling/v1beta1 QueueState."""

    OPEN = "Open"
    CLOSED = "Closed"
    CLOSING = "Closing"
    UNKNOWN = "Unknown"


class NodePhase(enum.IntEnum):
    """NodePhase (types.go:87-104)."""

    READY = 1
    NOT_READY = 2


class JobPhase(str, enum.Enum):
    """batch/v1alpha1 Job phases (vendor .../batch/v1alpha1/job.go)."""

    PENDING = "Pending"
    ABORTING = "Aborting"
    ABORTED = "Aborted"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    COMPLETING = "Completing"
    COMPLETED = "Completed"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"
    FAILED = "Failed"


class BusAction(str, enum.Enum):
    """bus/v1alpha1 Actions (vendor .../bus/v1alpha1/actions.go:20-60)."""

    ABORT_JOB = "AbortJob"
    RESTART_JOB = "RestartJob"
    RESTART_TASK = "RestartTask"
    TERMINATE_JOB = "TerminateJob"
    COMPLETE_JOB = "CompleteJob"
    RESUME_JOB = "ResumeJob"
    SYNC_JOB = "SyncJob"
    ENQUEUE_JOB = "EnqueueJob"
    SYNC_QUEUE = "SyncQueue"
    OPEN_QUEUE = "OpenQueue"
    CLOSE_QUEUE = "CloseQueue"


class BusEvent(str, enum.Enum):
    """bus/v1alpha1 Events (vendor .../bus/v1alpha1/events.go)."""

    ANY = "*"
    POD_FAILED = "PodFailed"
    POD_EVICTED = "PodEvicted"
    JOB_UNKNOWN = "Unknown"
    TASK_COMPLETED = "TaskCompleted"
    OUT_OF_SYNC = "OutOfSync"
    COMMAND_ISSUED = "CommandIssued"
    JOB_UPDATED = "JobUpdated"


# Legal task status transitions (types.go:107-110 keeps this permissive; the
# strict checks live in JobInfo.UpdateTaskStatus callers).
def validate_status_update(old: TaskStatus, new: TaskStatus) -> None:
    return None


# Fit-failure reasons (unschedule_info.go and node predicate errors).
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"
NODE_POD_NUMBER_EXCEEDED = "node(s) pod number exceeded"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
TAINTS_UNTOLERATED = "node(s) had taints that the pod didn't tolerate"
NODE_AFFINITY_FAILED = "node(s) didn't match node affinity"
POD_AFFINITY_FAILED = "node(s) didn't match pod affinity/anti-affinity"
NODE_PORTS_FAILED = "node(s) didn't have free ports for the requested pod ports"
