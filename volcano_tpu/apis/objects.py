"""Store-level object definitions.

Field layout mirrors the reference CRDs:
- Job:      vendor/volcano.sh/apis/pkg/apis/batch/v1alpha1/job.go:48-105
- PodGroup: vendor/.../scheduling/v1beta1/types.go:165-194
- Queue:    vendor/.../scheduling/v1beta1/types.go:305-317
- Command:  vendor/.../bus/v1alpha1
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import (BusAction, BusEvent, JobPhase, PodGroupPhase, QueueState,
                   Resource, TaskStatus)

_uid = itertools.count()


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_uid)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid())
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[dict] = field(default_factory=list)
    creation_timestamp: float = field(default_factory=time.time)
    finalizers: List[str] = field(default_factory=list)
    resource_version: int = 0

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class PodTemplate:
    """Pod template inside a TaskSpec: the schedulable payload."""

    resources: Optional[Resource] = None           # per-replica request
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[dict] = field(default_factory=list)
    affinity: dict = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    priority: int = 1
    containers: List[dict] = field(default_factory=list)
    restart_policy: str = "OnFailure"
    env: List[dict] = field(default_factory=list)
    volumes: List[dict] = field(default_factory=list)


@dataclass
class LifecyclePolicy:
    """Job events→actions policy (batch/v1alpha1 LifecyclePolicy)."""

    # None = no event clause (an exitCode-only policy); admission rejects
    # specifying both, matching validate/util.go:60-66
    event: Optional[BusEvent] = None
    action: BusAction = BusAction.SYNC_JOB
    exit_code: Optional[int] = None
    timeout: Optional[float] = None


@dataclass
class TaskSpec:
    """One task template of a Job (batch/v1alpha1 TaskSpec)."""

    name: str = ""
    replicas: int = 1
    min_available: Optional[int] = None
    template: PodTemplate = field(default_factory=PodTemplate)
    policies: List[LifecyclePolicy] = field(default_factory=list)


@dataclass
class JobSpec:
    scheduler_name: str = "volcano"
    queue: str = "default"
    min_available: int = 0
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    max_retry: int = 3
    ttl_seconds_after_finished: Optional[float] = None
    priority_class_name: str = ""
    volumes: List[dict] = field(default_factory=list)
    # job succeeds once this many pods succeeded (job.go:104 MinSuccess)
    min_success: Optional[int] = None


@dataclass
class JobStatus:
    state: JobPhase = JobPhase.PENDING
    state_message: str = ""
    state_last_transition: float = field(default_factory=time.time)
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0
    retry_count: int = 0
    min_available: int = 0
    task_status_count: Dict[str, Dict[str, int]] = field(default_factory=dict)
    conditions: List[dict] = field(default_factory=list)
    # ControlledResources (job_controller_actions.go:446): resources this
    # job owns, e.g. "volume-pvc-<name>" -> pvc name
    controlled_resources: Dict[str, str] = field(default_factory=dict)


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    KIND = "Job"


@dataclass
class PodStatus:
    phase: str = "Pending"     # Pending/Running/Succeeded/Failed
    node_name: str = ""
    reason: str = ""
    conditions: List[dict] = field(default_factory=list)
    # main-container termination code — matched by exitCode lifecycle
    # policies (job.go:162-164)
    exit_code: Optional[int] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template: PodTemplate = field(default_factory=PodTemplate)
    scheduler_name: str = "volcano"
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"


@dataclass
class PVCStatus:
    phase: str = "Pending"            # Pending | Bound
    node: str = ""                    # assumed/bound topology


@dataclass
class PVC:
    """PersistentVolumeClaim mirror — the job IO objects
    createJobIOIfNotExist manages (job_controller_actions.go:442-494)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: Dict = field(default_factory=dict)      # claim spec (size, class)
    status: PVCStatus = field(default_factory=PVCStatus)

    KIND = "PersistentVolumeClaim"


@dataclass
class PodGroupStatus:
    phase: PodGroupPhase = PodGroupPhase.PENDING
    conditions: List[dict] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroupSpec:
    min_member: int = 0
    queue: str = "default"
    priority_class_name: str = ""
    min_resources: Optional[Resource] = None


@dataclass
class PodGroupCR:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    KIND = "PodGroup"


@dataclass
class QueueStatus:
    state: QueueState = QueueState.OPEN
    unknown: int = 0
    pending: int = 0
    running: int = 0
    inqueue: int = 0


@dataclass
class QueueSpecCR:
    weight: int = 1
    capability: Optional[Resource] = None
    reclaimable: bool = True


@dataclass
class QueueCR:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: QueueSpecCR = field(default_factory=QueueSpecCR)
    status: QueueStatus = field(default_factory=QueueStatus)

    KIND = "Queue"


@dataclass
class ResourceQuota:
    """core/v1 ResourceQuota mirror — the scheduler reads ONLY the
    volcano.sh/namespace.weight key of spec.hard, which feeds drf's
    namespace fairness (event_handlers.go:740-770 updateResourceQuota,
    namespace_info.go NamespaceWeightKey)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, float] = field(default_factory=dict)   # spec.hard

    KIND = "ResourceQuota"


@dataclass
class PriorityClass:
    """scheduling.k8s.io PriorityClass (resolved into JobInfo.priority by the
    cache wiring, mirroring event_handlers.go AddPriorityClass:633)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False

    KIND = "PriorityClass"


@dataclass
class PartitionStateCR:
    """Federated control-plane state as a store object
    (docs/federation.md, store-backed transport): the PartitionMap's
    queue/node ownership + pin/drain markers and the ReserveLedger's
    open request set, flowing through the same CAS/watch path as every
    other CR. ``spec`` is one plain dict (queue_owner, node_owner,
    pinned, draining, rr_queue, rr_node, idle, requests, next_rid,
    version) so the CAS funnel can deep-copy/replace it wholesale —
    partial writes cannot exist, which is what makes an ownership flip
    atomic at the store."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: Dict = field(default_factory=dict)

    KIND = "PartitionState"


@dataclass
class Command:
    """bus/v1alpha1 Command: async RPC from CLI to controllers."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    action: BusAction = BusAction.SYNC_JOB
    target_object: Optional[dict] = None    # owner reference
    reason: str = ""
    message: str = ""

    KIND = "Command"
