"""CRD-like object model for the store/controllers layer.

Mirrors the vendored volcano.sh/apis module (SURVEY.md §2.6): batch/v1alpha1
Job, scheduling/v1beta1 PodGroup + Queue, bus/v1alpha1 Command, plus a
minimal core/v1 Pod. These are the objects that live in the ObjectStore (the
in-process etcd/API-server); the scheduler's api.* infos are built FROM them
by the cache's event handlers.
"""

from .objects import (Command, Job, JobSpec, LifecyclePolicy, Pod, PodGroupCR,
                      PodTemplate, PriorityClass, QueueCR, TaskSpec)

__all__ = ["Command", "Job", "JobSpec", "LifecyclePolicy", "Pod",
           "PodGroupCR", "PodTemplate", "PriorityClass", "QueueCR",
           "TaskSpec"]
