"""CycleBudget: the per-cycle work bound of the overload failure model
(docs/robustness.md).

Under sustained overload the scheduling cycle's natural cost grows with
the backlog — an unbounded cycle stretches the schedule period, which
grows the backlog further, which stretches the cycle: the collapse
spiral. The budget breaks it: ``Scheduler(cycle_budget_s=...)`` threads
one ``CycleBudget`` through ``run_once``; every action consults the
remaining budget before it dispatches, and when the budget is exhausted
the remaining actions DEFER to the next cycle with carry-over ordering
(a round-robin cursor persisted across cycles, so a deferred action is
the FIRST to run next cycle and no queue's action starves behind an
expensive neighbor).

Two spending meters compose:

- **elapsed time** on the injectable clock (``time_fn``) — the
  production meter: a slow device solve or a fat replay eats budget by
  simply taking wall time (the sim's VirtualClock does not advance
  inside a cycle, so this meter reads 0 under replay);
- **charged cost** (``charge``) — an explicit, deterministic work model:
  the shell charges ``budget_cost_fn(action, session)`` seconds-
  equivalent per action. The simulator prices actions by backlog size,
  which makes budget exhaustion a pure function of the decision plane —
  the overload soaks replay byte-identically.

The budget bounds work BETWEEN actions, not inside one — a single
action that overshoots finishes (nothing is half-applied), which is why
the acceptance bound is "p99 cycle spend within 2x the budget", not 1x.
``vlint`` rule VT018 (docs/static-analysis.md) statically pins the
companion contract: loops over pending/backlog collections in
scheduler-cycle scope must consult a budget/limit witness.
"""

from __future__ import annotations

from typing import Callable, Optional


class CycleBudget:
    """One cycle's spending record. Construct at cycle start; ``spent``
    is elapsed clock time since construction plus everything charged."""

    __slots__ = ("budget_s", "time_fn", "started", "charged")

    def __init__(self, budget_s: Optional[float],
                 time_fn: Callable[[], float]):
        self.budget_s = float(budget_s) if budget_s else None
        self.time_fn = time_fn
        self.started = time_fn()
        self.charged = 0.0

    def charge(self, cost_s: float) -> None:
        """Add deterministic modelled work (seconds-equivalent) to the
        cycle's spend; negative charges are ignored."""
        if cost_s > 0:
            self.charged += float(cost_s)

    def spent(self) -> float:
        return (self.time_fn() - self.started) + self.charged

    def remaining(self) -> float:
        """Seconds of budget left; +inf when unbounded."""
        if self.budget_s is None:
            return float("inf")
        return self.budget_s - self.spent()

    def exhausted(self) -> bool:
        """True once the cycle has spent its whole budget — the check
        every action runs BEFORE dispatch (a started action always
        finishes; the budget bounds work between actions)."""
        return self.budget_s is not None and self.remaining() <= 0.0

    def detail(self) -> dict:
        return {
            "budget_s": self.budget_s,
            "spent_s": round(self.spent(), 6),
            "charged_s": round(self.charged, 6),
            "exhausted": self.exhausted(),
        }
