"""Scheduler shell: the periodic scheduling loop.

Mirrors /root/reference/pkg/scheduler/scheduler.go:39-170 — 1s-period
runOnce over the configured action pipeline, YAML conf hot-reload (mtime
watch replacing the fsnotify filewatcher, pkg/filewatcher), per-action
latency metrics (scheduler.go:104-108).

Fault isolation (docs/robustness.md): one raised exception anywhere in an
action must not abort the cycle or kill the run() thread. run_once
isolates each action — a failing action is logged, counted
(metrics.register_action_failure) and skipped while the session still
closes and later actions still run — and run() wraps the whole cycle in a
crash-loop guard: consecutive failed cycles back off exponentially with
jitter and flip the exported health state to "degraded" (the /healthz
endpoint of metrics.start_metrics_server answers 503 until a clean cycle
resets it).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, List, Optional, Tuple

from . import metrics
from .framework import (abandon_session, close_session, get_action,
                        open_session, parse_scheduler_conf)
from .framework.conf import SchedulerConfiguration
from .obs import audit as obs_audit
from .obs import lifecycle as obs_lifecycle
from .obs import trace as obs_trace

log = logging.getLogger(__name__)

DEFAULT_SCHEDULE_PERIOD = 1.0


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "on", "yes")


class _Speculation:
    """One in-flight speculation: the speculative session (read-only
    staged snapshot, own GC window) and the dispatched-but-unfetched
    solve. Purely in-memory — nothing is journaled until the commit
    boundary, so a crash between dispatch and commit loses exactly this
    object and nothing else (the zero-double-binds contract of the
    pipelined chaos soak)."""

    __slots__ = ("ssn", "pending", "engine")

    def __init__(self, ssn, pending, engine: str):
        self.ssn = ssn
        self.pending = pending
        self.engine = engine


class _SpecCommitPlan:
    """A conflict-check verdict that lets the speculation commit: carried
    into the cycle's allocate slot, where _commit_speculation awaits the
    solve and replays it. ``promoted`` means the speculative session
    itself became the cycle's session (full hit). ``avoid_nodes`` names
    completion-shrunk nodes the tolerable-delta widening admitted on the
    PROMISE that the speculative solve placed nothing there — checked
    against the actual solution once it is fetched; a broken promise
    downgrades to the serial re-solve."""

    __slots__ = ("pending", "engine", "outcome", "spec_ssn", "promoted",
                 "avoid_nodes")

    def __init__(self, spec: _Speculation, outcome: str, promoted: bool,
                 avoid_nodes=frozenset()):
        self.pending = spec.pending
        self.engine = spec.engine
        self.outcome = outcome
        self.spec_ssn = spec.ssn
        self.promoted = promoted
        self.avoid_nodes = frozenset(avoid_nodes)

# crash-loop guard defaults: first failed cycle waits backoff_base, each
# consecutive failure doubles it up to backoff_max, each wait is stretched
# by up to backoff_jitter (uniform) so a fleet of replicas crash-looping on
# the same poison input doesn't retry in lockstep.
DEFAULT_BACKOFF_BASE = 1.0
DEFAULT_BACKOFF_MAX = 60.0
DEFAULT_BACKOFF_JITTER = 0.2

# per-cycle resync retry cap when a cycle budget is configured
# (docs/robustness.md overload failure model): the resync pass runs
# before the budget exists, so it carries its own work bound
DEFAULT_RESYNC_MAX_PER_CYCLE = 256

# Shadow-verifier cadence (docs/robustness.md): every N cycles the cache
# re-derives snapshot/tensor state from scratch OFF-CYCLE (outside the
# e2e-timed window) and repairs any drift. 0 disables; the env var
# overrides the constructor default.
DEFAULT_DRIFT_VERIFY_EVERY = 64

# HA role state machine (docs/robustness.md HA section). STANDALONE is
# the no-elector mode (every pre-HA deployment); with an elector attached
# the shell moves follower -> candidate -> leader, demotes to FENCED on a
# mid-cycle lease loss (the open session is abandoned, never
# half-applied), and a fenced replica re-enters as follower subject to
# the elector's flap cool-down.
ROLE_STANDALONE = "standalone"
ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"
ROLE_LEADER = "leader"
ROLE_FENCED = "fenced"


def _drift_verify_default() -> int:
    try:
        return int(os.environ.get("VOLCANO_TPU_DRIFT_VERIFY_EVERY",
                                  DEFAULT_DRIFT_VERIFY_EVERY))
    except ValueError:
        return DEFAULT_DRIFT_VERIFY_EVERY


class WallClock:
    """Default time source for the shell's pacing: monotonic wall time
    with a stop-interruptible sleep. The simulator (volcano_tpu/sim)
    swaps in a VirtualClock whose sleep advances virtual time and returns
    immediately — the run() loop then paces on virtual cycles with zero
    wall sleeps while everything else (metrics perf_counter timings) still
    measures real latency."""

    def __init__(self, stop_event: threading.Event):
        self._stop = stop_event

    def time(self) -> float:
        return time.monotonic()

    def now(self) -> float:
        """Wall-clock seconds since the epoch — the timebase shared with
        job creation_timestamps and cross-process lease records. time()
        stays monotonic for pacing/interval math; now() is for
        timestamps that are compared against externally-sourced ones.
        The sim's VirtualClock serves both from virtual time."""
        return time.time()

    def sleep(self, seconds: float) -> None:
        self._stop.wait(seconds)


class Scheduler:
    def __init__(self, cache, conf_text: Optional[str] = None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = DEFAULT_SCHEDULE_PERIOD,
                 backoff_base: float = DEFAULT_BACKOFF_BASE,
                 backoff_max: float = DEFAULT_BACKOFF_MAX,
                 backoff_jitter: float = DEFAULT_BACKOFF_JITTER,
                 clock=None,
                 drift_verify_every: Optional[int] = None,
                 rng: Optional[random.Random] = None,
                 pipelined: Optional[bool] = None,
                 fast_admit: Optional[bool] = None,
                 cycle_budget_s: Optional[float] = None,
                 budget_cost_fn: Optional[Callable] = None,
                 solve_deadline_s: Optional[float] = None,
                 resync_max_per_cycle: Optional[int] = None):
        # actions/plugins register on import
        from . import actions as _actions  # noqa: F401
        from . import plugins as _plugins  # noqa: F401
        self.cache = cache
        self.conf_path = conf_path
        self.schedule_period = schedule_period
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.backoff_jitter = backoff_jitter
        self._conf_mtime: Optional[float] = None
        self._stop = threading.Event()
        # time-source hook (time()/sleep()): wall clock by default, the
        # sim's VirtualClock under trace replay — run()'s period pacing
        # and crash-loop backoff go through it instead of time.sleep
        self.clock = clock or WallClock(self._stop)
        # Injectable RNG for crash-loop backoff jitter (vlint VT003).
        # Production wants per-process entropy (a fleet crash-looping on
        # the same poison input must not retry in lockstep), so the
        # default instance is entropy-seeded; the sim passes a
        # random.Random(seed) so failed-cycle backoff advances virtual
        # time deterministically.
        self._rng = rng if rng is not None else random.Random()
        self.conf: SchedulerConfiguration = None
        # pre-action hook (name, session) -> None; raising makes the action
        # count as failed. The chaos harness's ActionFaultInjector plugs in
        # here (volcano_tpu.chaos) — tests and soak rigs inject action
        # faults without reaching into the global action registry.
        self.action_fault_hook: Optional[Callable] = None
        # crash-loop guard state, exported through metrics.set_health
        self.consecutive_failures = 0
        # drift self-healing (docs/robustness.md): run_once counts cycles
        # and triggers the cache's shadow verifier off-cycle every N
        self.drift_verify_every = _drift_verify_default() \
            if drift_verify_every is None else drift_verify_every
        self._cycles_run = 0
        self._reconciled = False
        # HA (docs/robustness.md): no elector -> standalone, the historical
        # single-process behavior, zero new work per cycle. attach_elector
        # flips the shell into the role state machine.
        self.elector = None
        self.role = ROLE_STANDALONE
        self.last_handoff_report = None
        # sim hook: a restart harness points this at the cluster-truth
        # oracle for the previous leader's crash window; consumed (once)
        # by the handoff reconcile when this replica becomes leader.
        self.reconcile_oracle_fn: Optional[Callable] = None
        # sim hook mirroring action_fault_hook for the close boundary:
        # called (with the open session) right before close_session so a
        # seeded SimKill can land INSIDE the close — the adversarial
        # point where binds executed but writebacks didn't.
        self.close_fault_hook: Optional[Callable] = None
        # federation (docs/federation.md): a PartitionMember when this
        # scheduler runs one partition of a federated control plane.
        # Driven at the cycle boundaries — on_cycle_start BEFORE the
        # snapshot (incoming reserves granted against pre-cycle state),
        # on_cycle_end in the epilogue — and only while this replica
        # leads its partition (the hooks sit behind the HA gate).
        self.federation = None
        # elastic-gang lifecycle verbs (docs/design/elastic-gangs.md): a
        # CommandFunnel when command-driven suspend/resume/scale is
        # enabled. Drained exactly once per cycle, at the boundary AFTER
        # the federation hooks and BEFORE the snapshot, so a verb's
        # annotation rewrite is atomic w.r.t. scheduling decisions.
        self.command_funnel = None
        # pipelined scheduling (docs/performance.md): overlap cycle N+1's
        # device solve with cycle N's host commit via a speculative
        # session + conflict check at the commit boundary. Standalone
        # single-scheduler mode only — with an elector or federation
        # attached the shell silently runs serial cycles (leadership and
        # partition boundaries change between cycles; a speculation
        # cannot carry across them).
        self.pipelined = _env_flag("VOLCANO_TPU_PIPELINED") \
            if pipelined is None else bool(pipelined)
        self._spec: Optional[_Speculation] = None
        # sim hook (docs/simulation.md): called with the in-flight
        # _Speculation right after dispatch, so a seeded SimKill can land
        # BETWEEN speculative dispatch and commit — the adversarial point
        # where only speculative state may be lost.
        self.spec_fault_hook: Optional[Callable] = None
        # introspection for bench/tests: outcome of the last pipelined
        # commit ({"outcome": hit|partial|conflict|none, ...})
        self.last_speculation: dict = {}
        # event-driven fast-admit (docs/performance.md): bind
        # trivially-fitting gangs between full cycles through the
        # journaled+fenced bind funnel
        self.fast_admit_enabled = _env_flag("VOLCANO_TPU_FAST_ADMIT") \
            if fast_admit is None else bool(fast_admit)
        if self.fast_admit_enabled \
                and hasattr(self.cache, "fast_admit_feed"):
            self.cache.fast_admit_feed = True
        self._fast_admit_audit: list = []
        # overload resilience (docs/robustness.md overload failure
        # model): a per-cycle work bound. None (default) = unbounded —
        # the historical behavior, byte-identical decision plane. With a
        # budget set, every action checks the remaining budget before
        # dispatch; exhausted cycles defer the remaining actions to the
        # next cycle with carry-over ordering (_carryover is the
        # round-robin cursor: the first deferred action name, persisted
        # across cycles so no action starves behind an expensive one).
        self.cycle_budget_s = cycle_budget_s
        # deterministic work model for the budget (the sim prices
        # actions by backlog size so exhaustion replays byte-identically;
        # production leaves this None and spends wall time)
        self.budget_cost_fn = budget_cost_fn
        # hard deadline for the allocate slot: a device solve slower
        # than this is treated as a device fault — the device_health
        # cool-down opens and allocate degrades to the CPU placer for
        # the window (a hung/thrashing accelerator must not stall the
        # control plane; docs/robustness.md)
        self.solve_deadline_s = solve_deadline_s
        # the resync walk's per-cycle cap (cache.process_resync_tasks
        # max_items; vlint VT018): capped-out retries stay queued,
        # already ready, and drain next cycle. Defaults to bounded
        # whenever a cycle budget is set — the resync pass runs BEFORE
        # the budget is constructed, so this is its work bound — and
        # unbounded otherwise (the historical, byte-identical behavior).
        self.resync_max_per_cycle = resync_max_per_cycle \
            if resync_max_per_cycle is not None \
            else (DEFAULT_RESYNC_MAX_PER_CYCLE if cycle_budget_s
                  else None)
        self._carryover: Optional[str] = None
        self.last_budget = None
        self.budget_exhausted_total = 0
        self.deferred_actions_total = 0
        # high-water per-cycle spend (the overload soak's "p99 within
        # 2x budget" witness reads this off the report)
        self.max_cycle_spend_s = 0.0
        # warm-start witness (docs/performance.md): did the LAST cycle's
        # allocate fixpoint converge at the empty admitted set? Tracked
        # here per cycle — the module-global LAST_STATS is overwritten by
        # the commit path's suffix run, so it cannot serve as the witness
        self._warmstart_empty = False
        self._load_conf(conf_text)

    # -- HA role state machine (docs/robustness.md) --------------------------

    def attach_elector(self, elector) -> None:
        """Enter HA mode: this replica schedules only while ``elector``
        holds the lease. Every journaled side effect is stamped with the
        elector's fencing epoch (the cache funnels read it through
        fencing_epoch_fn), so a deposed incarnation's writes are
        rejectable at the executor gate."""
        self.elector = elector
        self.role = ROLE_FOLLOWER
        # shell-level leadership edge detector: the become-leader branch
        # of the gate (handoff reconcile, failover metric) must fire on
        # the FIRST gated cycle of every leadership, regardless of
        # whether the threaded elector.run() or the cycle-driven step()
        # flipped elector.leading first
        self._was_leading = False
        if hasattr(self.cache, "fencing_epoch_fn"):
            self.cache.fencing_epoch_fn = self.current_fencing_epoch
        metrics.set_leader(False, self.role, 0)

    def current_fencing_epoch(self) -> int:
        return self.elector.fencing_epoch if self.elector is not None else 0

    def _ha_gate(self, rec) -> bool:
        """The per-cycle leadership gate: one election/renew step. Returns
        True when this replica may run the cycle (it leads). On a fresh
        acquisition the handoff runs startup_reconcile BEFORE the first
        cycle — the journal's crash window (a dead predecessor's
        unsettled intent) is settled against cluster truth, which is what
        bounds failover to lease-acquire -> reconcile -> resume."""
        elector = self.elector
        led_before = self._was_leading
        with rec.span("elect", role=self.role):
            leading = elector.step()
        if not leading:
            self._was_leading = False
            # a fenced ex-leader re-enters as an ordinary follower here:
            # FENCED only describes the demoted remainder of the cycle
            # the lease was lost in (contention throttling is the flap
            # guard's job, not a role)
            self.role = ROLE_FOLLOWER
            metrics.set_leader(False, self.role, elector.fencing_epoch)
            if self.federation is not None:
                # keep the per-partition leadership gauge honest: the
                # leader-gated cycle hooks never run here, so the
                # follower state must be published from the gate itself
                self.federation.publish_follower()
            return False
        if not led_before:
            # epoch 1 is the first-ever leadership; any later acquisition
            # (takeover of a foreign lease, or re-claiming after a loss)
            # is a leadership transition — a failover
            takeover = elector.fencing_epoch > 1
            with rec.span("handoff", epoch=elector.fencing_epoch,
                          takeover=takeover):
                oracle = None
                if self.reconcile_oracle_fn is not None:
                    oracle = self.reconcile_oracle_fn()
                try:
                    if oracle is not None:
                        self.last_handoff_report = \
                            self.startup_reconcile(*oracle)
                    else:
                        self.last_handoff_report = self.startup_reconcile()
                except Exception:
                    log.exception("handoff journal reconciliation failed; "
                                  "continuing (side effects may retry)")
            if takeover:
                metrics.register_failover()
            log.warning("replica %s became leader (epoch %d)",
                        elector.identity, elector.fencing_epoch)
        self.role = ROLE_LEADER
        self._was_leading = True
        metrics.set_leader(True, self.role, elector.fencing_epoch)
        return True

    def _demoted_mid_cycle(self) -> bool:
        """True when HA mode is on and leadership was lost since the
        cycle's gate passed (the renew watchdog or a revocation flipped
        ``elector.leading``). The action loop checks this between
        actions; a demoted leader abandons the open session rather than
        half-applying it."""
        if self.elector is None or self.elector.leading:
            return False
        self.role = ROLE_FENCED
        self._was_leading = False
        metrics.set_leader(False, self.role, self.elector.fencing_epoch)
        return True

    def _load_conf(self, conf_text: Optional[str] = None) -> None:
        if conf_text is None and self.conf_path and os.path.exists(self.conf_path):
            with open(self.conf_path) as f:
                conf_text = f.read()
            self._conf_mtime = os.path.getmtime(self.conf_path)
        self.conf = parse_scheduler_conf(conf_text)

    def _maybe_reload_conf(self) -> None:
        """Hot-reload on file change (scheduler.go:112-170)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return
        mtime = os.path.getmtime(self.conf_path)
        if mtime != self._conf_mtime:
            self._load_conf()

    def run_once(self) -> List[Tuple[str, BaseException]]:
        """One scheduling cycle (scheduler.go:90-110).

        Returns the isolated per-action failures of the cycle, [] when
        clean. A failing action is skipped — the session still closes and
        the remaining pipeline still runs; only a failure OUTSIDE the
        action loop (conf reload, snapshot/open_session, close_session)
        propagates to the caller, where run()'s guard catches it.

        The cycle is bracketed by the flight recorder
        (docs/observability.md): every run_once is one span tree
        (cycle → resync / schedule → open_session / action:* /
        close_session → audit / epilogue) in obs.TRACE's ring, and the
        per-action/e2e metrics histograms are fed FROM the spans, so
        timing is recorded once."""
        rec = obs_trace.TRACE
        cycle = self._cycles_run
        # pin the ambient correlation context (obs/lifecycle.py) every
        # funnel-level stamp of this cycle inherits; a federated member
        # also claims its own lane (pid) in the merged Chrome trace
        part = self.federation.pid if self.federation is not None \
            else getattr(self.cache, "obs_part", 0)
        if hasattr(self.cache, "obs_part"):
            self.cache.obs_part = part
        obs_lifecycle.TIMELINE.set_context(
            cycle=cycle, part=part, epoch=self.current_fencing_epoch(),
            t=self.clock.time())
        if self.federation is not None:
            rec.set_pid(part + 1)
        began = rec.enabled
        if began:
            rec.begin_cycle(cycle)
        try:
            with rec.span("cycle", cycle=cycle):
                # HA gate: a replica without the lease runs its election
                # step and NOTHING else — no resync retries (side effects
                # are the leader's), no snapshot, no session. run_once
                # refusing to open a session without a live lease IS the
                # standby contract.
                if self.elector is not None and not self._ha_gate(rec):
                    return []
                return self._run_once_traced(rec, cycle)
        finally:
            if began:
                rec.end_cycle()

    def _run_once_traced(self, rec, cycle: int
                         ) -> List[Tuple[str, BaseException]]:
        self._maybe_reload_conf()
        # retry failed side effects whose backoff expired (the reference's
        # errTasks worker goroutine, cache.go:777-799). Isolated like an
        # action: a cache retry fault must not cost the scheduling cycle.
        errors: List[Tuple[str, BaseException]] = []
        if hasattr(self.cache, "process_resync_tasks"):
            try:
                with rec.span("resync"):
                    self.cache.process_resync_tasks(
                        self.resync_max_per_cycle)
            except Exception as exc:
                log.exception("resync processing failed")
                metrics.register_action_failure("resync")
                errors.append(("resync", exc))
        # federated cycle boundary (docs/federation.md): expire timed-out
        # reserves, settle drained queue moves, review incoming reserve
        # requests — BEFORE the snapshot, so grants (evictions, node
        # transfers) shape the state this cycle schedules against.
        # Isolated like an action; a SimKill inside a drain eviction
        # tunnels (it is not an Exception), exactly like the funnels it
        # rides through.
        if self.federation is not None:
            try:
                with rec.span("federation"):
                    self.federation.on_cycle_start()
            except Exception as exc:
                log.exception("federation cycle-start hook failed")
                metrics.register_action_failure("federation")
                errors.append(("federation", exc))
        # elastic-gang command funnel (docs/design/elastic-gangs.md):
        # apply queued suspend/resume/scale verbs against pre-snapshot
        # state — each apply journals a fenced command_applied record and
        # dirties the job, so this cycle's snapshot sees whole commands
        # or none. Isolated like an action.
        if self.command_funnel is not None:
            try:
                with rec.span("commands"):
                    self.command_funnel.consume()
            except Exception as exc:
                log.exception("command funnel consume failed")
                metrics.register_action_failure("commands")
                errors.append(("commands", exc))
        # A cycle whose pipeline resolves to NO runnable action is a no-op:
        # don't pay cache.snapshot() (re-cloning queues/jobs at 10k scale)
        # plus a full open/close just to run zero actions — the state a
        # degraded scheduler sits in when its conf names only unregistered
        # actions (bad hot-reload) and the crash-loop guard is skipping work.
        runnable = [(name, get_action(name)) for name in self.conf.actions]
        runnable = [(n, a) for n, a in runnable if a is not None]
        if not runnable:
            # resync retries above still journaled side effects, and the
            # drift cadence must keep counting — the short-circuit skips
            # only the snapshot/session work
            self._discard_speculation("conflict")
            self._cycle_epilogue()
            return errors
        # cycle deadline budget (docs/robustness.md overload failure
        # model): rotate the pipeline to the carry-over cursor BEFORE
        # anything runs — last cycle's deferred actions go first, so
        # every action gets budget within at most a pipeline-length of
        # cycles (fair round-robin; no queue starves behind an
        # expensive neighbor). No budget -> no rotation -> the
        # historical, byte-identical order.
        budget = None
        if self.cycle_budget_s:
            from .cycle_budget import CycleBudget
            budget = CycleBudget(self.cycle_budget_s, self.clock.time)
            self.last_budget = budget
            if self._carryover is not None:
                names = [n for n, _ in runnable]
                if self._carryover in names:
                    ix = names.index(self._carryover)
                    runnable = runnable[ix:] + runnable[:ix]
                self._carryover = None
        # pipelined commit boundary (docs/performance.md): decide what the
        # in-flight speculation is worth BEFORE opening anything — a full
        # hit promotes the speculative session (the staged snapshot is
        # adopted and no real open runs at all); a tolerable delta opens a
        # fresh session and replays the speculative solve onto it; any
        # real divergence discards the speculation and the cycle re-solves
        # serially.
        pipelined = (self.pipelined and self.elector is None
                     and self.federation is None)
        ssn = None
        commit = None
        if self._spec is not None:
            spec, self._spec = self._spec, None
            if pipelined and any(n in ("allocate", "allocate-tpu")
                                 for n, _ in runnable):
                with rec.span("conflict_check"):
                    ssn, commit = self._check_speculation(rec, spec)
            else:
                self._abandon_speculation(spec, "conflict")
        sched_sp = rec.span("schedule")
        crashed = False
        demoted = False
        with sched_sp:
            if ssn is None:
                with rec.span("open_session"):
                    ssn = open_session(self.cache, self.conf.tiers,
                                       self.conf.configurations,
                                       time_fn=self.clock.now)
            if self._fast_admit_audit and obs_audit.AUDIT.enabled:
                # fast-admit binds since the last cycle ride this cycle's
                # audit harvest (their jobs read "admitted" with the bind
                # count they earned between cycles)
                ssn.audit_events.extend(self._fast_admit_audit)
                self._fast_admit_audit.clear()
            try:
                for act_ix, (name, action) in enumerate(runnable):
                    if budget is not None and act_ix > 0 \
                            and budget.exhausted():
                        # budget spent: defer the REST of the pipeline
                        # to the next cycle (the first action of a cycle
                        # always runs — a budget can bound work, never
                        # starve the pipeline outright). The cursor
                        # persists the deferral so the deferred actions
                        # run FIRST next cycle.
                        deferred = [n for n, _ in runnable[act_ix:]]
                        self._carryover = name
                        self.budget_exhausted_total += 1
                        self.deferred_actions_total += len(deferred)
                        metrics.register_cycle_budget_exhausted(name)
                        metrics.register_deferred_actions(len(deferred))
                        log.warning(
                            "cycle budget exhausted (%.3fs spent of "
                            "%.3fs); deferring %s to the next cycle",
                            budget.spent(), budget.budget_s, deferred)
                        break
                    if self._demoted_mid_cycle():
                        # the lease was lost while the cycle ran: stop
                        # scheduling NOW. Already-executed side effects
                        # carried a then-valid epoch; anything we would
                        # issue from here on is a deposed leader's write
                        # (the fencing gate would reject it anyway) —
                        # and the open session must not be half-applied,
                        # so close-time writebacks are skipped below.
                        demoted = True
                        log.warning("lease lost mid-cycle; demoting to "
                                    "fenced and abandoning the open "
                                    "session")
                        break
                    action_sp = rec.span("action:" + name, action=name)
                    poisoned = False
                    try:
                        with action_sp:
                            try:
                                if self.action_fault_hook is not None:
                                    self.action_fault_hook(name, ssn)
                                if commit is not None and name in (
                                        "allocate", "allocate-tpu"):
                                    plan, commit = commit, None
                                    self._commit_speculation(ssn, plan,
                                                             action)
                                elif pipelined and name in (
                                        "allocate", "allocate-tpu"):
                                    action.execute(ssn)
                                    self._warmstart_empty = bool(
                                        self._allocate_kept_empty())
                                else:
                                    action.execute(ssn)
                            except Exception as exc:
                                log.exception("action %s failed; skipping "
                                              "it this cycle", name)
                                metrics.register_action_failure(name)
                                errors.append((name, exc))
                                poisoned = getattr(exc, "poisons_session",
                                                   False)
                    finally:
                        metrics.update_action_duration(name,
                                                       action_sp.dur_s)
                    if budget is not None \
                            and self.budget_cost_fn is not None:
                        # deterministic work model (the sim's meter):
                        # price the action by what it processed so
                        # exhaustion is a pure function of the decision
                        # plane — a broken cost model must not break
                        # the cycle
                        try:
                            budget.charge(self.budget_cost_fn(name, ssn))
                        except Exception:
                            log.exception("budget cost model failed; "
                                          "action %s not charged", name)
                    if self.solve_deadline_s is not None \
                            and name in ("allocate", "allocate-tpu") \
                            and action_sp.dur_s > self.solve_deadline_s:
                        # a hung/slow device solve past the hard
                        # deadline is contained like a device fault:
                        # the cool-down opens and allocate degrades to
                        # the CPU placer until the window expires —
                        # the same path an XLA OOM rides
                        from .device_health import DEVICE_HEALTH
                        DEVICE_HEALTH.record_fault("slow_solve")
                        log.error(
                            "device solve took %.3fs (hard deadline "
                            "%.3fs); opening the device cool-down — "
                            "allocate degrades to the CPU placer",
                            action_sp.dur_s, self.solve_deadline_s)
                    if poisoned:
                        # the action mutated session state outside any
                        # undo log (allocate.ReplayFault): later actions
                        # would schedule against phantom aggregates —
                        # abort the rest of the cycle, keep the loop alive
                        log.error("action %s poisoned the session; "
                                  "aborting the remaining actions this "
                                  "cycle", name)
                        break
                if commit is not None:
                    # the allocate slot never ran (budget deferral or a
                    # poisoned earlier action broke the loop): the
                    # in-flight speculation cannot carry across — retire
                    # its pinned epoch and count the conflict
                    plan, commit = commit, None
                    self._finish_speculation(plan, "conflict")
                if budget is not None:
                    self.max_cycle_spend_s = max(self.max_cycle_spend_s,
                                                 budget.spent())
                if not demoted and self._demoted_mid_cycle():
                    demoted = True       # lost during the last action
            except BaseException as exc:
                # a non-Exception escaping here is a (simulated or real)
                # process death — SimKill, KeyboardInterrupt. A SIGKILL'd
                # process never runs close-time writebacks (plugin
                # on_session_close, the job updater's PodGroup status
                # flush), so neither may we: skip close_session and let the
                # session's leak finalizer resume the GC window instead.
                crashed = not isinstance(exc, Exception)
                raise
            finally:
                if not crashed:
                    if demoted:
                        # session ROLLBACK path: resume the GC window but
                        # run neither plugin on_session_close nor the
                        # podgroup status flush — a fenced ex-leader may
                        # not publish decision state it no longer owns
                        abandon_session(ssn)
                    else:
                        with rec.span("close_session"):
                            if self.close_fault_hook is not None:
                                self.close_fault_hook(ssn)
                            close_session(ssn)
        metrics.update_e2e_duration(sched_sp.dur_s)
        # decision audit (docs/observability.md): harvested AFTER
        # close_session so the gang plugin's job_fit_errors writeback is
        # the denial reason, outside the e2e-timed window
        if not demoted and obs_audit.AUDIT.enabled:
            try:
                with rec.span("audit"):
                    obs_audit.harvest_cycle(ssn, cycle, self.clock.time())
            except Exception:
                log.exception("decision-audit harvest failed")
        # stage 2 of the pipeline: dispatch cycle N+1's speculative solve
        # while this cycle's tail (epilogue, pacing sleep, fast-admit) and
        # the device transfer overlap. Outside the e2e-timed window.
        if pipelined and not demoted:
            self._dispatch_speculation(rec, runnable)
        self._cycle_epilogue()
        return errors

    def _cycle_epilogue(self) -> None:
        """Off-cycle (post-e2e-window) cycle bookkeeping, run on BOTH
        run_once exits: flush the journal's buffered ack tail (intents
        are made durable before their executor runs; this just bounds
        ack-record lag to one cycle) and tick the drift-verify cadence."""
        with obs_trace.TRACE.span("epilogue"):
            journal = getattr(self.cache, "journal", None)
            if journal is not None:
                try:
                    journal.flush()
                except Exception:
                    log.exception("journal flush failed")
            # store-wired caches: resume torn watch streams, tick
            # bookmarks, reset the retry funnel's per-cycle budget
            # (cache/watches.WatchManager; docs/robustness.md store
            # failure model). Isolated — stream upkeep failing must not
            # cost the cycle; the next epilogue retries.
            manager = getattr(self.cache, "watch_manager", None)
            if manager is not None:
                try:
                    with obs_trace.TRACE.span("watch_upkeep"):
                        manager.step()
                except Exception:
                    log.exception("watch-stream upkeep failed")
            # ack watchdog (docs/robustness.md feedback failure model):
            # drain delayed watch-path acks and re-validate in-flight
            # entries whose cluster ack is overdue — the liveness
            # guarantee that nothing stays in flight forever. Isolated:
            # a watchdog fault costs this pass, not the cycle.
            if hasattr(self.cache, "process_expired_inflight"):
                try:
                    with obs_trace.TRACE.span("inflight_watchdog"):
                        self.cache.process_expired_inflight()
                except Exception:
                    log.exception("in-flight ack watchdog failed")
            if self.federation is not None:
                try:
                    self.federation.on_cycle_end()
                except Exception:
                    log.exception("federation cycle-end hook failed")
                    metrics.register_action_failure("federation")
            self._maybe_verify_drift()

    # -- pipelined speculation (docs/performance.md) -------------------------

    def _check_speculation(self, rec, spec: _Speculation):
        """The commit-boundary conflict check: diff what actually mutated
        since the speculative snapshot was staged against what the
        speculation assumed. Returns ``(session_or_None, plan_or_None)``:

        - CLEAN (no mutation at all): the staged snapshot is adopted and
          the speculative session PROMOTES to this cycle's real session —
          no open_session runs.
        - TOLERABLE delta (only decision-neutral changes — bind acks,
          plus brand-new jobs the suffix solve will cover): a fresh
          session opens and the speculative solve replays onto it by uid.
        - anything else: the speculation is discarded (conflict) and the
          cycle re-solves serially.
        """
        delta = self.cache.speculation_delta(spec.ssn.spec_basis)
        clean = not (delta["epoch_moved"] or delta["nodes"]
                     or delta["jobs"] or delta["queues"])
        if clean and self.cache.adopt_speculative_snapshot(
                spec.ssn.spec_basis):
            ssn = spec.ssn
            ssn.speculative = False     # promoted: the cycle's real session
            return ssn, _SpecCommitPlan(spec, "hit", promoted=True)
        if clean or delta["epoch_moved"] or delta["queues"]:
            # clean-but-adopt-refused is a stage/adopt race; epoch or
            # queue movement is never tolerable (ordering/overuse inputs)
            self._abandon_speculation(spec, "conflict")
            return None, None
        with rec.span("open_session"):
            ssn = open_session(self.cache, self.conf.tiers,
                               self.conf.configurations,
                               time_fn=self.clock.now)
        avoid = self._delta_tolerable(spec, ssn, delta)
        if avoid is None:
            self._abandon_speculation(spec, "conflict")
            return ssn, None
        plan = _SpecCommitPlan(spec, "partial", promoted=False,
                               avoid_nodes=avoid)
        # the solution objects live on through the plan's pending; the
        # speculative session itself (GC window, pinned epoch) releases
        # now — nothing journaled, nothing half-applied
        abandon_session(spec.ssn)
        return ssn, plan

    def _delta_tolerable(self, spec: _Speculation, ssn, delta):
        """May the speculative solve still commit onto ``ssn`` despite
        the delta? Returns the set of COMPLETION-SHRUNK node names the
        commit must verify the solution avoided (possibly empty), or
        None when the delta is intolerable.

        Tolerable classes (docs/performance.md, ROADMAP item 2):

        - a changed node/known job that is DECISION-EQUAL between the
          speculative and the fresh snapshot (bind acks — BOUND→RUNNING
          — the canonical case: accounting, pending sets and gang
          counters all unchanged);
        - a changed job that is NEW (unknown at speculation time; the
          commit's suffix solve owns those);
        - a job that VANISHED (its gang completed / was deleted): if the
          solve covered it anyway, the uid remap fails and the commit
          downgrades to serial — nothing can half-apply;
        - a node that only SHED tasks (a completion freed capacity,
          nothing else changed): tolerable iff the speculation placed
          nothing there, which only the fetched solution can prove —
          hence the returned avoid set, enforced in _commit_speculation.
          Extra capacity the speculation did not use cannot invalidate
          its placements; jobs it rejected stay pending and the next
          cycle's solve sees the freed node."""
        sspec = spec.ssn
        avoid = set()
        for name in delta["nodes"]:
            a = sspec.nodes.get(name)
            b = ssn.nodes.get(name)
            if a is None and b is None:
                continue
            if a is None or b is None:
                return None             # node appeared/left: re-solve
            if self._node_decision_equal(a, b):
                continue
            if self._node_completion_shrunk(a, b):
                avoid.add(name)
                continue
            return None
        for uid in delta["jobs"]:
            a = sspec.jobs.get(uid)
            if a is None:
                continue                # new job: suffix solve covers it
            b = ssn.jobs.get(uid)
            if b is None:
                continue                # vanished: remap guard owns it
            if not self._job_decision_equal(a, b):
                return None
        return avoid

    @staticmethod
    def _node_decision_equal(a, b) -> bool:
        """Do two snapshot clones of one node feed the solve identical
        inputs? Compares exactly what reaches the kernels and the mask
        builders (accounting vectors, capacity, schedulability, task
        population) — NOT task statuses, which is what makes bind acks
        tolerable."""
        if (a.allocatable is not b.allocatable
                or a.unschedulable != b.unschedulable
                or a.ready != b.ready
                or a.max_task_num != b.max_task_num
                or len(a.tasks) != len(b.tasks)
                or set(a.tasks) != set(b.tasks)
                or a.used_ports != b.used_ports):
            return False
        for f in ("idle", "used", "releasing", "pipelined"):
            if getattr(a, f) != getattr(b, f):
                return False
        return True

    @staticmethod
    def _solution_touches(mapped, avoid_nodes) -> bool:
        """Did the (remapped) speculative solution place any task on one
        of ``avoid_nodes``? The commit-time enforcement of the
        completion-shrunk tolerable-delta class."""
        import numpy as np
        from .actions.allocate import NO_NODE
        tn = np.asarray(mapped.task_node)
        placed = {mapped.node_t.names[int(n)]
                  for n in np.unique(tn[tn != NO_NODE])}
        return bool(placed & set(avoid_nodes))

    @staticmethod
    def _node_completion_shrunk(a, b) -> bool:
        """Did node ``b`` (fresh) differ from ``a`` (speculative) ONLY
        by tasks leaving — a completion delta? Identity/capacity fields
        unchanged, the fresh task set a strict subset of the speculative
        one, and every surviving task unchanged. Freed capacity cannot
        invalidate placements made elsewhere; whether anything was
        placed HERE is the commit-time avoid-set check."""
        if (a.allocatable is not b.allocatable
                or a.unschedulable != b.unschedulable
                or a.ready != b.ready
                or a.max_task_num != b.max_task_num):
            return False
        if not set(b.tasks) < set(a.tasks):
            return False
        return all(b.tasks[u].status == a.tasks[u].status
                   and b.tasks[u].node_name == a.tasks[u].node_name
                   for u in b.tasks)

    @staticmethod
    def _job_decision_equal(a, b) -> bool:
        from .api import TaskStatus
        if (a.queue != b.queue or a.priority != b.priority
                or a.min_available != b.min_available
                or a.podgroup is None or b.podgroup is None
                or a.podgroup.phase != b.podgroup.phase
                or a.ready_task_num() != b.ready_task_num()
                or a.waiting_task_num() != b.waiting_task_num()):
            return False
        return set(a.task_status_index.get(TaskStatus.PENDING, {})) \
            == set(b.task_status_index.get(TaskStatus.PENDING, {}))

    def _commit_speculation(self, ssn, plan: "_SpecCommitPlan",
                            action) -> None:
        """The allocate slot of a pipelined cycle whose conflict check
        passed: await the speculative solve (its one sanctioned
        readback), re-anchor it onto the session by uid, continue the
        serial fixpoint from it (gang rollbacks re-solve exactly as the
        serial cycle would), then suffix-solve the jobs the speculation
        could not know about. Every placement replays through the same
        Statement/bind funnels as a serial cycle — speculation changes
        WHEN the solve ran, never how its decisions commit. Any failure
        inside the speculative machinery downgrades to the configured
        serial action within the same cycle."""
        from .actions import allocate as alloc
        alloc.LAST_FALLBACK.clear()
        spec_mesh = getattr(plan.pending, "mesh_devices", None)
        if spec_mesh is not None \
                and alloc.current_mesh_ids(ssn) != tuple(spec_mesh):
            # the mesh changed between dispatch and commit — a device was
            # quarantined (its shard of the packed result is gone) or
            # readmitted (the live layout re-padded to a different D).
            # Either way the dispatched result is unusable: classify as
            # conflict, which retires the pinned epoch pair, and re-solve
            # serially over the mesh as it is NOW.
            log.warning("mesh changed under speculation (%s -> %s): "
                        "conflict, re-solving serially", spec_mesh,
                        alloc.current_mesh_ids(ssn))
            self._finish_speculation(plan, "conflict")
            action.execute(ssn)
            self._warmstart_empty = self._allocate_kept_empty()
            return
        mapped = ordered = None
        try:
            sol = alloc.finalize_speculative_dispatch(plan.pending)
            mapped, ordered = alloc.remap_speculative_solution(
                sol, plan.pending.ordered_jobs, ssn)
        except Exception:
            log.exception("speculative solve unusable; re-solving the "
                          "cycle serially")
        if mapped is not None and plan.avoid_nodes \
                and self._solution_touches(mapped, plan.avoid_nodes):
            # the completion-shrunk widening's promise check: the delta
            # was tolerable only if the speculation placed nothing on
            # the nodes that shed tasks — the fetched solution is the
            # proof. A placement there means the solve reasoned about
            # pre-completion capacity: discard and re-solve serially.
            mapped = None
        if mapped is None:
            self._finish_speculation(plan, "conflict")
            action.execute(ssn)
            self._warmstart_empty = self._allocate_kept_empty()
            return
        hint = plan.pending.assumed_hint
        if hint is not None:
            # warm-started speculation: sound ONLY if the fixpoint stayed
            # where the warm-start assumed (kept == hint, i.e. the
            # saturated ∅ fixpoint). Anything else re-solves serially —
            # continuing from a foreign premise could diverge from the
            # serial trajectory on an otherwise-clean cycle.
            kept = {mapped.jobs_list[jx].uid
                    for jx in range(len(mapped.jobs_list))
                    if mapped.job_kept[jx]}
            if kept != hint:
                self._finish_speculation(plan, "conflict")
                action.execute(ssn)
                self._warmstart_empty = self._allocate_kept_empty()
                return
        kernel = "scan" if plan.engine == "tpu-scan" else "auto"
        with obs_trace.TRACE.span("speculate_commit",
                                  outcome=plan.outcome):
            alloc._execute_fused(ssn, kernel=kernel, first_solution=mapped,
                                 first_ordered=ordered, first_assumed=hint)
            # the warm-start witness must be the MAIN fixpoint's verdict;
            # read it before the suffix run overwrites LAST_STATS
            main_empty = bool(alloc.LAST_STATS.get("final_kept_empty"))
            suffix = ({j.uid for j in alloc._eligible_jobs(ssn)}
                      - plan.pending.eligible_uids)
            if suffix:
                alloc._execute_fused(ssn, kernel=kernel, only_jobs=suffix)
                # a suffix that ADMITTED jobs moved the fixpoint: the ∅
                # warm-start would only be discarded at the next commit
                main_empty = main_empty and bool(
                    alloc.LAST_STATS.get("final_kept_empty"))
            self._warmstart_empty = main_empty
        self._finish_speculation(plan, plan.outcome)

    def _finish_speculation(self, plan: "_SpecCommitPlan",
                            outcome: str) -> None:
        from .framework.framework import _retire_session_pin
        _retire_session_pin(plan.spec_ssn)
        metrics.register_speculation(outcome)
        self.last_speculation = {"outcome": outcome,
                                 "promoted": plan.promoted}

    def _abandon_speculation(self, spec: _Speculation,
                             outcome: str) -> None:
        basis = spec.ssn.spec_basis
        abandon_session(spec.ssn)       # retires the pinned epoch too
        if basis is not None:
            # give the moved dirty keys back (no-op if a real snapshot
            # already reabsorbed them)
            discard = getattr(self.cache, "discard_speculative_snapshot",
                              None)
            if discard is not None:
                discard(basis)
        metrics.register_speculation(outcome)
        self.last_speculation = {"outcome": outcome, "promoted": False}

    def _discard_speculation(self, outcome: str) -> None:
        if self._spec is not None:
            spec, self._spec = self._spec, None
            self._abandon_speculation(spec, outcome)

    @staticmethod
    def _allocate_kept_empty() -> bool:
        from .actions.allocate import LAST_STATS
        return bool(LAST_STATS.get("final_kept_empty"))

    def _allocate_engine(self, runnable) -> Optional[str]:
        """The engine the allocate slot will run, when it is one the
        dispatch/await split supports — every fused device kernel: scan,
        pallas (packed device decode), and the unified sharded engine."""
        for name, action in runnable:
            if name not in ("allocate", "allocate-tpu"):
                continue
            engine = getattr(action, "engine", None) or "callbacks"
            for c in self.conf.configurations:
                if c.name in (name, "allocate"):
                    engine = c.arguments.get("engine", engine)
            return engine if engine in ("tpu-fused", "tpu-scan",
                                        "tpu-pallas", "tpu-sharded") else None
        return None

    def _dispatch_speculation(self, rec, runnable) -> None:
        """Stage 2 of the pipeline: open a speculative session on the
        post-commit state and DISPATCH cycle N+1's solve. jax async
        dispatch returns immediately, so the device crunches while the
        host finishes the epilogue and sleeps out the period. Nothing
        here touches the journal or the executors (vlint VT015): a crash
        between this dispatch and the next commit loses only the
        speculation."""
        engine = self._allocate_engine(runnable)
        if engine is None:
            return
        with rec.span("speculate", engine=engine):
            sssn = None
            try:
                sssn = open_session(self.cache, self.conf.tiers,
                                    self.conf.configurations,
                                    time_fn=self.clock.now,
                                    speculative=True)
                from .actions.allocate import dispatch_speculative_solve
                # warm-start at the ∅ fixpoint iff this cycle's fused
                # fixpoint CONVERGED empty (saturated backlog): the next
                # serial cycle would converge there again, so solving at
                # the fixpoint directly skips its in-cycle re-solve. The
                # witness is shell-tracked (_warmstart_empty) — set from
                # the MAIN fixpoint at commit, not from whatever
                # _execute_fused ran last.
                hint = set() if self._warmstart_empty else None
                pending = dispatch_speculative_solve(sssn, engine,
                                                     assumed_hint=hint)
                if pending is None:
                    abandon_session(sssn)
                    return
                self._spec = _Speculation(sssn, pending, engine)
            except Exception:
                # a broken speculation must never cost the cycle that
                # already committed — log, drop, run serial next cycle
                log.exception("speculative dispatch failed; next cycle "
                              "runs serial")
                if sssn is not None:
                    abandon_session(sssn)
                self._spec = None
                return
            except BaseException:
                # SimKill / process death mid-speculation: only the
                # in-memory speculative state is lost — nothing was
                # journaled, so recovery cannot double-bind
                self._spec = None
                raise
            if self.spec_fault_hook is not None:
                # sim hook: lands a seeded SimKill BETWEEN dispatch and
                # commit — the speculation exists, nothing is journaled
                self.spec_fault_hook(self._spec)

    # -- event-driven fast admit (docs/performance.md) -----------------------

    def fast_admit(self, max_gangs: int = 64) -> int:
        """Bind trivially-fitting gangs BETWEEN full cycles, so p99
        time-to-first-bind drops below one cycle period. Trivial means
        provably interaction-free: the whole gang fits one node's idle
        AND future_idle (pipelined reservations respected), no placement
        constraints (selectors/affinity/tolerations/topology), no
        gpu-card or NUMA packing, no preempt/reclaim involvement, and —
        for PENDING podgroups — the unconditional enqueue path
        (``min_resources is None``, exactly EnqueueAction's gate). Binds
        ride the journaled+fenced ``bind_batch`` funnel and are fed into
        the next cycle's audit harvest; anything not provably trivial
        waits for the full cycle. Returns the number of tasks bound.

        Any bind here dirties the cache, so an in-flight speculation
        over the pre-admit state conflicts at its commit boundary — the
        two fast paths compose without a special case."""
        if not self.fast_admit_enabled:
            return 0
        if self.elector is not None and not self.elector.leading:
            return 0
        cache = self.cache
        drain = getattr(cache, "drain_new_jobs", None)
        if drain is None:
            return 0
        if self.federation is not None:
            # partitioned control plane: ownership is enforced at session
            # scope (cache.snapshot_scope), and this path reads the
            # whole-cluster indexes directly — binding here could claim
            # another partition's job. Standalone/HA-leader only; drain
            # the feed so it cannot grow unconsumed.
            drain()
            return 0
        uids = drain()
        if not uids:
            return 0
        from .api import PodGroupPhase
        gangs = tasks_bound = 0
        with obs_trace.TRACE.span("fast_admit", jobs=len(uids)):
            for uid in uids:
                if gangs >= max_gangs:
                    # cap the between-cycles work; the full cycle owns
                    # the rest (they stay in cache.jobs regardless)
                    break
                job = cache.jobs.get(uid)
                fit = self._trivial_fit(job)
                if fit is None:
                    continue
                node, gang = fit
                if job.podgroup.phase == PodGroupPhase.PENDING:
                    # the unconditional branch of EnqueueAction's gate
                    job.podgroup.phase = PodGroupPhase.INQUEUE
                    cache.mark_job_dirty(uid)
                    cache.update_job_status(job)
                # the funnel convention (session.dispatch does the same):
                # the ARGUMENT task carries the placement, the cached
                # object must still be unplaced — that is what routes
                # bind_batch onto its fresh-placement path (journal
                # intent fresh=True, full rollback on binder failure).
                # Mutating the live task first would misclassify every
                # fast-admit bind as a re-bind.
                placed = []
                for task in gang:
                    ti = task.shallow_clone()
                    ti.node_name = node.name
                    placed.append(ti)
                cache.bind_batch(placed)
                gangs += 1
                tasks_bound += len(gang)
                if obs_audit.AUDIT.enabled:
                    for task in gang:
                        self._fast_admit_audit.append(
                            ("bind", task.uid, task.job, "fast-admit"))
        if gangs:
            metrics.register_fast_admit(gangs, tasks_bound)
        return tasks_bound

    def _trivial_fit(self, job):
        """(node, gang_tasks) when the WHOLE gang provably fits one node
        under the CPU placer's resource rule with zero interactions, else
        None. First fitting node in cache order — deterministic."""
        from .api import PodGroupPhase, Resource, TaskStatus
        cache = self.cache
        if job is None or job.podgroup is None:
            return None
        if job.podgroup.phase not in (PodGroupPhase.PENDING,
                                      PodGroupPhase.INQUEUE):
            return None
        if job.podgroup.phase == PodGroupPhase.PENDING \
                and job.podgroup.min_resources is not None:
            return None                 # enqueue's vote path: not trivial
        if job.queue not in cache.queues:
            return None
        gang = [t for t in job.tasks.values()
                if t.status == TaskStatus.PENDING
                and not t.resreq.is_empty()]
        if not gang or len(gang) != len(job.tasks):
            return None                 # partially-placed gang: full cycle
        if not (0 < job.min_available <= len(gang)):
            return None
        total = Resource()
        for task in gang:
            if (task.node_selector or task.affinity or task.tolerations
                    or task.topology_policy or task.revocable_zone
                    or getattr(task, "_has_pod_affinity", False)):
                return None             # placement constraints: full cycle
            total.add(task.init_resreq)
        inflight = set(cache.binding_tasks.values())
        for node in cache.nodes.values():
            if (not node.ready or node.unschedulable
                    or node.name in inflight
                    or node.gpu_devices or node.numa_info is not None):
                continue
            if any(t.get("effect") in ("NoSchedule", "NoExecute")
                   for t in node.taints):
                continue
            if node.max_task_num > 0 \
                    and len(node.tasks) + len(gang) > node.max_task_num:
                continue
            if total.less_equal(node.idle) \
                    and total.less_equal(node.future_idle()):
                return node, gang
        return None

    def _maybe_verify_drift(self) -> None:
        """Amortized shadow verification (docs/robustness.md): every
        ``drift_verify_every`` cycles, AFTER the e2e-timed window closed,
        ask the cache to re-derive snapshot/tensor state from scratch and
        self-heal any drift. Isolated like an action — a verifier bug
        must not cost scheduling cycles."""
        self._cycles_run += 1
        if not self.drift_verify_every \
                or self._cycles_run % self.drift_verify_every:
            return
        verify = getattr(self.cache, "verify_state_integrity", None)
        if verify is None:
            return
        try:
            stats = verify()
            if stats["drift_total"]:
                log.error("state drift detected and repaired: %s",
                          stats["drift"])
        except Exception:
            log.exception("shadow drift verification failed")
            metrics.register_action_failure("drift-verify")

    def startup_reconcile(self, cluster_binds=None, cluster_evicts=None):
        """Settle the intent journal's crash window before the first
        cycle (cache.reconcile_journal); called automatically by run(),
        explicitly by restart harnesses. Idempotent per process."""
        self._reconciled = True
        reconcile = getattr(self.cache, "reconcile_journal", None)
        if reconcile is None:
            return None
        report = reconcile(cluster_binds, cluster_evicts)
        if report is not None and report.replayed:
            log.warning("journal reconciliation replayed %d unacked "
                        "intents: %s", report.replayed, report.as_dict())
        # the in-flight ledger died with the old process while the
        # settled state still shows BOUND/RELEASING tasks whose cluster
        # ack is outstanding: re-arm their deadlines so an ack lost
        # around the crash meets the watchdog (docs/robustness.md
        # feedback failure model)
        rearm = getattr(self.cache, "rearm_inflight_from_state", None)
        if rearm is not None:
            try:
                rearm()
            except Exception:
                log.exception("re-arming the in-flight ledger failed")
        return report

    def _backoff(self, cap: float) -> float:
        """Exponential backoff with jitter for the current consecutive
        failure count (>= 1), capped at ``cap``."""
        n = max(self.consecutive_failures, 1)
        delay = min(self.backoff_base * (2 ** (n - 1)), cap)
        return delay * (1.0 + self._rng.uniform(0.0, self.backoff_jitter))

    def run(self) -> None:
        """wait.Until(runOnce, period) (scheduler.go:81-88), with the
        crash-loop guard: a failed cycle increments the consecutive
        failure count, flips health to degraded and waits a jittered
        exponential backoff instead of the schedule period; a clean cycle
        resets both. The backoff cap depends on the blast radius: an
        exception ESCAPING run_once (snapshot/session machinery — nothing
        scheduled) backs off up to backoff_max, while isolated per-action
        faults (the rest of the pipeline ran fine) cap near the schedule
        period — one chronically failing action must not throttle healthy
        actions and the resync retries to crash-loop cadence."""
        if not self._reconciled:
            try:
                self.startup_reconcile()
            except Exception:
                log.exception("startup journal reconciliation failed; "
                              "continuing (side effects may retry)")
        while not self._stop.is_set():
            if self.fast_admit_enabled:
                # between-cycles fast path: arrivals that accumulated
                # during the pacing sleep bind now instead of waiting out
                # the rest of the period
                try:
                    self.fast_admit()
                except Exception:
                    log.exception("fast-admit pass failed; the full "
                                  "cycle will pick the jobs up")
            cycle_start = time.perf_counter()
            cycle_fault = False
            try:
                errors = self.run_once()
            except Exception as exc:
                log.exception("scheduling cycle failed outside the action "
                              "pipeline")
                errors = [("cycle", exc)]
                cycle_fault = True
            if errors:
                self.consecutive_failures += 1
                metrics.set_health(metrics.DEGRADED,
                                   self.consecutive_failures)
                cap = self.backoff_max if cycle_fault else \
                    max(self.schedule_period, self.backoff_base)
                self.clock.sleep(self._backoff(cap))
                continue
            if self.consecutive_failures:
                self.consecutive_failures = 0
            metrics.set_health(metrics.HEALTHY, 0)
            remaining = self.schedule_period - (time.perf_counter() - cycle_start)
            if remaining > 0:
                self.clock.sleep(remaining)

    def prewarm(self, configs=None) -> int:
        """Pre-trace/compile the configured allocate solver at the shape
        buckets the steady-state loop will hit, so cold-bucket XLA
        compiles (a 6.5 s stall when a fresh arrival-batch bucket first
        appears mid-churn) pay at startup instead of inside a scheduling
        cycle.

        ``configs`` is an iterable of ``(tasks, jobs)`` shape hints — the
        pending-task count and the number of jobs owning them for each
        cycle shape to warm (task counts snap to the engine's pow2
        buckets, so one entry covers its whole bucket). None derives a
        single entry from the cache's current pending set. Engines
        resolve exactly as AllocateAction.execute does (conf
        ``configurations`` override the action default); the callback
        engines compile nothing and return 0. Returns the number of
        shapes warmed."""
        from .framework import close_session, get_action, open_session
        engine = None
        for name in self.conf.actions:
            if name not in ("allocate", "allocate-tpu"):
                continue
            action = get_action(name)
            engine = getattr(action, "engine", None) or "callbacks"
            for c in self.conf.configurations:
                if c.name in (name, "allocate"):
                    engine = c.arguments.get("engine", engine)
            break
        # the preempt walk warms too (its (preemptor, victim-slot) axes
        # bucket pow2 — evict_tpu.prewarm_preempt mirrors the live path)
        preempt_engine = None
        if "preempt" in self.conf.actions:
            action = get_action("preempt")
            preempt_engine = getattr(action, "engine", None) or "callbacks"
            for c in self.conf.configurations:
                if c.name == "preempt":
                    preempt_engine = c.arguments.get("engine",
                                                     preempt_engine)
        if (engine is None or engine.startswith("callbacks")) \
                and preempt_engine not in ("tpu", "tpu-sharded"):
            return 0
        from .actions.allocate import prewarm_shapes
        ssn = open_session(self.cache, self.conf.tiers,
                           self.conf.configurations,
                           time_fn=self.clock.now)
        try:
            warmed = prewarm_shapes(ssn, configs,
                                    engine or "callbacks",
                                    preempt_engine=preempt_engine)
            if self.pipelined:
                # the cold epoch-pair allocation (device upload + pinned
                # host copies + future-idle program) belongs here, not in
                # the first pipelined cycle — the 708ms first-churn-cycle
                # outlier was exactly this cost landing in-cycle
                tc = getattr(self.cache, "tensor_cache", None)
                if tc is not None and hasattr(tc, "prewarm_epoch_pair"):
                    tc.prewarm_epoch_pair()
            return warmed
        finally:
            close_session(ssn)

    def run_with_leader_election(self, store, name: str = "vc-scheduler",
                                 **lease_kwargs) -> None:
        """HA entry point (cmd/scheduler/app/server.go:111-141): block until
        this replica holds the lease in the store, then run the loop; losing
        the lease stops it."""
        from .leaderelection import LeaderElector
        self._elector = LeaderElector(
            store, name, on_started_leading=self.run,
            on_stopped_leading=self.stop, **lease_kwargs)
        self.attach_elector(self._elector)
        self._elector.run()

    def start(self) -> threading.Thread:
        thread = threading.Thread(target=self.run, daemon=True,
                                  name="vc-scheduler")
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()
