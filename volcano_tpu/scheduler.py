"""Scheduler shell: the periodic scheduling loop.

Mirrors /root/reference/pkg/scheduler/scheduler.go:39-170 — 1s-period
runOnce over the configured action pipeline, YAML conf hot-reload (mtime
watch replacing the fsnotify filewatcher, pkg/filewatcher), per-action
latency metrics (scheduler.go:104-108).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

from . import metrics
from .framework import (close_session, get_action, open_session,
                        parse_scheduler_conf)
from .framework.conf import SchedulerConfiguration

DEFAULT_SCHEDULE_PERIOD = 1.0


class Scheduler:
    def __init__(self, cache, conf_text: Optional[str] = None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = DEFAULT_SCHEDULE_PERIOD):
        # actions/plugins register on import
        from . import actions as _actions  # noqa: F401
        from . import plugins as _plugins  # noqa: F401
        self.cache = cache
        self.conf_path = conf_path
        self.schedule_period = schedule_period
        self._conf_mtime: Optional[float] = None
        self._stop = threading.Event()
        self.conf: SchedulerConfiguration = None
        self._load_conf(conf_text)

    def _load_conf(self, conf_text: Optional[str] = None) -> None:
        if conf_text is None and self.conf_path and os.path.exists(self.conf_path):
            with open(self.conf_path) as f:
                conf_text = f.read()
            self._conf_mtime = os.path.getmtime(self.conf_path)
        self.conf = parse_scheduler_conf(conf_text)

    def _maybe_reload_conf(self) -> None:
        """Hot-reload on file change (scheduler.go:112-170)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return
        mtime = os.path.getmtime(self.conf_path)
        if mtime != self._conf_mtime:
            self._load_conf()

    def run_once(self) -> None:
        """One scheduling cycle (scheduler.go:90-110)."""
        self._maybe_reload_conf()
        # retry failed side effects whose backoff expired (the reference's
        # errTasks worker goroutine, cache.go:777-799)
        if hasattr(self.cache, "process_resync_tasks"):
            self.cache.process_resync_tasks()
        start = time.perf_counter()
        ssn = open_session(self.cache, self.conf.tiers,
                           self.conf.configurations)
        try:
            for name in self.conf.actions:
                action = get_action(name)
                if action is None:
                    continue
                action_start = time.perf_counter()
                action.execute(ssn)
                metrics.update_action_duration(
                    name, time.perf_counter() - action_start)
        finally:
            close_session(ssn)
        metrics.update_e2e_duration(time.perf_counter() - start)

    def run(self) -> None:
        """wait.Until(runOnce, period) (scheduler.go:81-88)."""
        while not self._stop.is_set():
            cycle_start = time.perf_counter()
            self.run_once()
            remaining = self.schedule_period - (time.perf_counter() - cycle_start)
            if remaining > 0:
                self._stop.wait(remaining)

    def run_with_leader_election(self, store, name: str = "vc-scheduler",
                                 **lease_kwargs) -> None:
        """HA entry point (cmd/scheduler/app/server.go:111-141): block until
        this replica holds the lease in the store, then run the loop; losing
        the lease stops it."""
        from .leaderelection import LeaderElector
        self._elector = LeaderElector(
            store, name, on_started_leading=self.run,
            on_stopped_leading=self.stop, **lease_kwargs)
        self._elector.run()

    def start(self) -> threading.Thread:
        thread = threading.Thread(target=self.run, daemon=True,
                                  name="vc-scheduler")
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()
