"""Scheduler shell: the periodic scheduling loop.

Mirrors /root/reference/pkg/scheduler/scheduler.go:39-170 — 1s-period
runOnce over the configured action pipeline, YAML conf hot-reload (mtime
watch replacing the fsnotify filewatcher, pkg/filewatcher), per-action
latency metrics (scheduler.go:104-108).

Fault isolation (docs/robustness.md): one raised exception anywhere in an
action must not abort the cycle or kill the run() thread. run_once
isolates each action — a failing action is logged, counted
(metrics.register_action_failure) and skipped while the session still
closes and later actions still run — and run() wraps the whole cycle in a
crash-loop guard: consecutive failed cycles back off exponentially with
jitter and flip the exported health state to "degraded" (the /healthz
endpoint of metrics.start_metrics_server answers 503 until a clean cycle
resets it).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, List, Optional, Tuple

from . import metrics
from .framework import (abandon_session, close_session, get_action,
                        open_session, parse_scheduler_conf)
from .framework.conf import SchedulerConfiguration
from .obs import audit as obs_audit
from .obs import trace as obs_trace

log = logging.getLogger(__name__)

DEFAULT_SCHEDULE_PERIOD = 1.0

# crash-loop guard defaults: first failed cycle waits backoff_base, each
# consecutive failure doubles it up to backoff_max, each wait is stretched
# by up to backoff_jitter (uniform) so a fleet of replicas crash-looping on
# the same poison input doesn't retry in lockstep.
DEFAULT_BACKOFF_BASE = 1.0
DEFAULT_BACKOFF_MAX = 60.0
DEFAULT_BACKOFF_JITTER = 0.2

# Shadow-verifier cadence (docs/robustness.md): every N cycles the cache
# re-derives snapshot/tensor state from scratch OFF-CYCLE (outside the
# e2e-timed window) and repairs any drift. 0 disables; the env var
# overrides the constructor default.
DEFAULT_DRIFT_VERIFY_EVERY = 64

# HA role state machine (docs/robustness.md HA section). STANDALONE is
# the no-elector mode (every pre-HA deployment); with an elector attached
# the shell moves follower -> candidate -> leader, demotes to FENCED on a
# mid-cycle lease loss (the open session is abandoned, never
# half-applied), and a fenced replica re-enters as follower subject to
# the elector's flap cool-down.
ROLE_STANDALONE = "standalone"
ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"
ROLE_LEADER = "leader"
ROLE_FENCED = "fenced"


def _drift_verify_default() -> int:
    try:
        return int(os.environ.get("VOLCANO_TPU_DRIFT_VERIFY_EVERY",
                                  DEFAULT_DRIFT_VERIFY_EVERY))
    except ValueError:
        return DEFAULT_DRIFT_VERIFY_EVERY


class WallClock:
    """Default time source for the shell's pacing: monotonic wall time
    with a stop-interruptible sleep. The simulator (volcano_tpu/sim)
    swaps in a VirtualClock whose sleep advances virtual time and returns
    immediately — the run() loop then paces on virtual cycles with zero
    wall sleeps while everything else (metrics perf_counter timings) still
    measures real latency."""

    def __init__(self, stop_event: threading.Event):
        self._stop = stop_event

    def time(self) -> float:
        return time.monotonic()

    def now(self) -> float:
        """Wall-clock seconds since the epoch — the timebase shared with
        job creation_timestamps and cross-process lease records. time()
        stays monotonic for pacing/interval math; now() is for
        timestamps that are compared against externally-sourced ones.
        The sim's VirtualClock serves both from virtual time."""
        return time.time()

    def sleep(self, seconds: float) -> None:
        self._stop.wait(seconds)


class Scheduler:
    def __init__(self, cache, conf_text: Optional[str] = None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = DEFAULT_SCHEDULE_PERIOD,
                 backoff_base: float = DEFAULT_BACKOFF_BASE,
                 backoff_max: float = DEFAULT_BACKOFF_MAX,
                 backoff_jitter: float = DEFAULT_BACKOFF_JITTER,
                 clock=None,
                 drift_verify_every: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        # actions/plugins register on import
        from . import actions as _actions  # noqa: F401
        from . import plugins as _plugins  # noqa: F401
        self.cache = cache
        self.conf_path = conf_path
        self.schedule_period = schedule_period
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.backoff_jitter = backoff_jitter
        self._conf_mtime: Optional[float] = None
        self._stop = threading.Event()
        # time-source hook (time()/sleep()): wall clock by default, the
        # sim's VirtualClock under trace replay — run()'s period pacing
        # and crash-loop backoff go through it instead of time.sleep
        self.clock = clock or WallClock(self._stop)
        # Injectable RNG for crash-loop backoff jitter (vlint VT003).
        # Production wants per-process entropy (a fleet crash-looping on
        # the same poison input must not retry in lockstep), so the
        # default instance is entropy-seeded; the sim passes a
        # random.Random(seed) so failed-cycle backoff advances virtual
        # time deterministically.
        self._rng = rng if rng is not None else random.Random()
        self.conf: SchedulerConfiguration = None
        # pre-action hook (name, session) -> None; raising makes the action
        # count as failed. The chaos harness's ActionFaultInjector plugs in
        # here (volcano_tpu.chaos) — tests and soak rigs inject action
        # faults without reaching into the global action registry.
        self.action_fault_hook: Optional[Callable] = None
        # crash-loop guard state, exported through metrics.set_health
        self.consecutive_failures = 0
        # drift self-healing (docs/robustness.md): run_once counts cycles
        # and triggers the cache's shadow verifier off-cycle every N
        self.drift_verify_every = _drift_verify_default() \
            if drift_verify_every is None else drift_verify_every
        self._cycles_run = 0
        self._reconciled = False
        # HA (docs/robustness.md): no elector -> standalone, the historical
        # single-process behavior, zero new work per cycle. attach_elector
        # flips the shell into the role state machine.
        self.elector = None
        self.role = ROLE_STANDALONE
        self.last_handoff_report = None
        # sim hook: a restart harness points this at the cluster-truth
        # oracle for the previous leader's crash window; consumed (once)
        # by the handoff reconcile when this replica becomes leader.
        self.reconcile_oracle_fn: Optional[Callable] = None
        # sim hook mirroring action_fault_hook for the close boundary:
        # called (with the open session) right before close_session so a
        # seeded SimKill can land INSIDE the close — the adversarial
        # point where binds executed but writebacks didn't.
        self.close_fault_hook: Optional[Callable] = None
        # federation (docs/federation.md): a PartitionMember when this
        # scheduler runs one partition of a federated control plane.
        # Driven at the cycle boundaries — on_cycle_start BEFORE the
        # snapshot (incoming reserves granted against pre-cycle state),
        # on_cycle_end in the epilogue — and only while this replica
        # leads its partition (the hooks sit behind the HA gate).
        self.federation = None
        self._load_conf(conf_text)

    # -- HA role state machine (docs/robustness.md) --------------------------

    def attach_elector(self, elector) -> None:
        """Enter HA mode: this replica schedules only while ``elector``
        holds the lease. Every journaled side effect is stamped with the
        elector's fencing epoch (the cache funnels read it through
        fencing_epoch_fn), so a deposed incarnation's writes are
        rejectable at the executor gate."""
        self.elector = elector
        self.role = ROLE_FOLLOWER
        # shell-level leadership edge detector: the become-leader branch
        # of the gate (handoff reconcile, failover metric) must fire on
        # the FIRST gated cycle of every leadership, regardless of
        # whether the threaded elector.run() or the cycle-driven step()
        # flipped elector.leading first
        self._was_leading = False
        if hasattr(self.cache, "fencing_epoch_fn"):
            self.cache.fencing_epoch_fn = self.current_fencing_epoch
        metrics.set_leader(False, self.role, 0)

    def current_fencing_epoch(self) -> int:
        return self.elector.fencing_epoch if self.elector is not None else 0

    def _ha_gate(self, rec) -> bool:
        """The per-cycle leadership gate: one election/renew step. Returns
        True when this replica may run the cycle (it leads). On a fresh
        acquisition the handoff runs startup_reconcile BEFORE the first
        cycle — the journal's crash window (a dead predecessor's
        unsettled intent) is settled against cluster truth, which is what
        bounds failover to lease-acquire -> reconcile -> resume."""
        elector = self.elector
        led_before = self._was_leading
        with rec.span("elect", role=self.role):
            leading = elector.step()
        if not leading:
            self._was_leading = False
            # a fenced ex-leader re-enters as an ordinary follower here:
            # FENCED only describes the demoted remainder of the cycle
            # the lease was lost in (contention throttling is the flap
            # guard's job, not a role)
            self.role = ROLE_FOLLOWER
            metrics.set_leader(False, self.role, elector.fencing_epoch)
            if self.federation is not None:
                # keep the per-partition leadership gauge honest: the
                # leader-gated cycle hooks never run here, so the
                # follower state must be published from the gate itself
                self.federation.publish_follower()
            return False
        if not led_before:
            # epoch 1 is the first-ever leadership; any later acquisition
            # (takeover of a foreign lease, or re-claiming after a loss)
            # is a leadership transition — a failover
            takeover = elector.fencing_epoch > 1
            with rec.span("handoff", epoch=elector.fencing_epoch,
                          takeover=takeover):
                oracle = None
                if self.reconcile_oracle_fn is not None:
                    oracle = self.reconcile_oracle_fn()
                try:
                    if oracle is not None:
                        self.last_handoff_report = \
                            self.startup_reconcile(*oracle)
                    else:
                        self.last_handoff_report = self.startup_reconcile()
                except Exception:
                    log.exception("handoff journal reconciliation failed; "
                                  "continuing (side effects may retry)")
            if takeover:
                metrics.register_failover()
            log.warning("replica %s became leader (epoch %d)",
                        elector.identity, elector.fencing_epoch)
        self.role = ROLE_LEADER
        self._was_leading = True
        metrics.set_leader(True, self.role, elector.fencing_epoch)
        return True

    def _demoted_mid_cycle(self) -> bool:
        """True when HA mode is on and leadership was lost since the
        cycle's gate passed (the renew watchdog or a revocation flipped
        ``elector.leading``). The action loop checks this between
        actions; a demoted leader abandons the open session rather than
        half-applying it."""
        if self.elector is None or self.elector.leading:
            return False
        self.role = ROLE_FENCED
        self._was_leading = False
        metrics.set_leader(False, self.role, self.elector.fencing_epoch)
        return True

    def _load_conf(self, conf_text: Optional[str] = None) -> None:
        if conf_text is None and self.conf_path and os.path.exists(self.conf_path):
            with open(self.conf_path) as f:
                conf_text = f.read()
            self._conf_mtime = os.path.getmtime(self.conf_path)
        self.conf = parse_scheduler_conf(conf_text)

    def _maybe_reload_conf(self) -> None:
        """Hot-reload on file change (scheduler.go:112-170)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return
        mtime = os.path.getmtime(self.conf_path)
        if mtime != self._conf_mtime:
            self._load_conf()

    def run_once(self) -> List[Tuple[str, BaseException]]:
        """One scheduling cycle (scheduler.go:90-110).

        Returns the isolated per-action failures of the cycle, [] when
        clean. A failing action is skipped — the session still closes and
        the remaining pipeline still runs; only a failure OUTSIDE the
        action loop (conf reload, snapshot/open_session, close_session)
        propagates to the caller, where run()'s guard catches it.

        The cycle is bracketed by the flight recorder
        (docs/observability.md): every run_once is one span tree
        (cycle → resync / schedule → open_session / action:* /
        close_session → audit / epilogue) in obs.TRACE's ring, and the
        per-action/e2e metrics histograms are fed FROM the spans, so
        timing is recorded once."""
        rec = obs_trace.TRACE
        cycle = self._cycles_run
        began = rec.enabled
        if began:
            rec.begin_cycle(cycle)
        try:
            with rec.span("cycle", cycle=cycle):
                # HA gate: a replica without the lease runs its election
                # step and NOTHING else — no resync retries (side effects
                # are the leader's), no snapshot, no session. run_once
                # refusing to open a session without a live lease IS the
                # standby contract.
                if self.elector is not None and not self._ha_gate(rec):
                    return []
                return self._run_once_traced(rec, cycle)
        finally:
            if began:
                rec.end_cycle()

    def _run_once_traced(self, rec, cycle: int
                         ) -> List[Tuple[str, BaseException]]:
        self._maybe_reload_conf()
        # retry failed side effects whose backoff expired (the reference's
        # errTasks worker goroutine, cache.go:777-799). Isolated like an
        # action: a cache retry fault must not cost the scheduling cycle.
        errors: List[Tuple[str, BaseException]] = []
        if hasattr(self.cache, "process_resync_tasks"):
            try:
                with rec.span("resync"):
                    self.cache.process_resync_tasks()
            except Exception as exc:
                log.exception("resync processing failed")
                metrics.register_action_failure("resync")
                errors.append(("resync", exc))
        # federated cycle boundary (docs/federation.md): expire timed-out
        # reserves, settle drained queue moves, review incoming reserve
        # requests — BEFORE the snapshot, so grants (evictions, node
        # transfers) shape the state this cycle schedules against.
        # Isolated like an action; a SimKill inside a drain eviction
        # tunnels (it is not an Exception), exactly like the funnels it
        # rides through.
        if self.federation is not None:
            try:
                with rec.span("federation"):
                    self.federation.on_cycle_start()
            except Exception as exc:
                log.exception("federation cycle-start hook failed")
                metrics.register_action_failure("federation")
                errors.append(("federation", exc))
        # A cycle whose pipeline resolves to NO runnable action is a no-op:
        # don't pay cache.snapshot() (re-cloning queues/jobs at 10k scale)
        # plus a full open/close just to run zero actions — the state a
        # degraded scheduler sits in when its conf names only unregistered
        # actions (bad hot-reload) and the crash-loop guard is skipping work.
        runnable = [(name, get_action(name)) for name in self.conf.actions]
        runnable = [(n, a) for n, a in runnable if a is not None]
        if not runnable:
            # resync retries above still journaled side effects, and the
            # drift cadence must keep counting — the short-circuit skips
            # only the snapshot/session work
            self._cycle_epilogue()
            return errors
        sched_sp = rec.span("schedule")
        crashed = False
        demoted = False
        with sched_sp:
            with rec.span("open_session"):
                ssn = open_session(self.cache, self.conf.tiers,
                                   self.conf.configurations,
                                   time_fn=self.clock.now)
            try:
                for name, action in runnable:
                    if self._demoted_mid_cycle():
                        # the lease was lost while the cycle ran: stop
                        # scheduling NOW. Already-executed side effects
                        # carried a then-valid epoch; anything we would
                        # issue from here on is a deposed leader's write
                        # (the fencing gate would reject it anyway) —
                        # and the open session must not be half-applied,
                        # so close-time writebacks are skipped below.
                        demoted = True
                        log.warning("lease lost mid-cycle; demoting to "
                                    "fenced and abandoning the open "
                                    "session")
                        break
                    action_sp = rec.span("action:" + name, action=name)
                    poisoned = False
                    try:
                        with action_sp:
                            try:
                                if self.action_fault_hook is not None:
                                    self.action_fault_hook(name, ssn)
                                action.execute(ssn)
                            except Exception as exc:
                                log.exception("action %s failed; skipping "
                                              "it this cycle", name)
                                metrics.register_action_failure(name)
                                errors.append((name, exc))
                                poisoned = getattr(exc, "poisons_session",
                                                   False)
                    finally:
                        metrics.update_action_duration(name,
                                                       action_sp.dur_s)
                    if poisoned:
                        # the action mutated session state outside any
                        # undo log (allocate.ReplayFault): later actions
                        # would schedule against phantom aggregates —
                        # abort the rest of the cycle, keep the loop alive
                        log.error("action %s poisoned the session; "
                                  "aborting the remaining actions this "
                                  "cycle", name)
                        break
                if not demoted and self._demoted_mid_cycle():
                    demoted = True       # lost during the last action
            except BaseException as exc:
                # a non-Exception escaping here is a (simulated or real)
                # process death — SimKill, KeyboardInterrupt. A SIGKILL'd
                # process never runs close-time writebacks (plugin
                # on_session_close, the job updater's PodGroup status
                # flush), so neither may we: skip close_session and let the
                # session's leak finalizer resume the GC window instead.
                crashed = not isinstance(exc, Exception)
                raise
            finally:
                if not crashed:
                    if demoted:
                        # session ROLLBACK path: resume the GC window but
                        # run neither plugin on_session_close nor the
                        # podgroup status flush — a fenced ex-leader may
                        # not publish decision state it no longer owns
                        abandon_session(ssn)
                    else:
                        with rec.span("close_session"):
                            if self.close_fault_hook is not None:
                                self.close_fault_hook(ssn)
                            close_session(ssn)
        metrics.update_e2e_duration(sched_sp.dur_s)
        # decision audit (docs/observability.md): harvested AFTER
        # close_session so the gang plugin's job_fit_errors writeback is
        # the denial reason, outside the e2e-timed window
        if not demoted and obs_audit.AUDIT.enabled:
            try:
                with rec.span("audit"):
                    obs_audit.harvest_cycle(ssn, cycle, self.clock.time())
            except Exception:
                log.exception("decision-audit harvest failed")
        self._cycle_epilogue()
        return errors

    def _cycle_epilogue(self) -> None:
        """Off-cycle (post-e2e-window) cycle bookkeeping, run on BOTH
        run_once exits: flush the journal's buffered ack tail (intents
        are made durable before their executor runs; this just bounds
        ack-record lag to one cycle) and tick the drift-verify cadence."""
        with obs_trace.TRACE.span("epilogue"):
            journal = getattr(self.cache, "journal", None)
            if journal is not None:
                try:
                    journal.flush()
                except Exception:
                    log.exception("journal flush failed")
            if self.federation is not None:
                try:
                    self.federation.on_cycle_end()
                except Exception:
                    log.exception("federation cycle-end hook failed")
                    metrics.register_action_failure("federation")
            self._maybe_verify_drift()

    def _maybe_verify_drift(self) -> None:
        """Amortized shadow verification (docs/robustness.md): every
        ``drift_verify_every`` cycles, AFTER the e2e-timed window closed,
        ask the cache to re-derive snapshot/tensor state from scratch and
        self-heal any drift. Isolated like an action — a verifier bug
        must not cost scheduling cycles."""
        self._cycles_run += 1
        if not self.drift_verify_every \
                or self._cycles_run % self.drift_verify_every:
            return
        verify = getattr(self.cache, "verify_state_integrity", None)
        if verify is None:
            return
        try:
            stats = verify()
            if stats["drift_total"]:
                log.error("state drift detected and repaired: %s",
                          stats["drift"])
        except Exception:
            log.exception("shadow drift verification failed")
            metrics.register_action_failure("drift-verify")

    def startup_reconcile(self, cluster_binds=None, cluster_evicts=None):
        """Settle the intent journal's crash window before the first
        cycle (cache.reconcile_journal); called automatically by run(),
        explicitly by restart harnesses. Idempotent per process."""
        self._reconciled = True
        reconcile = getattr(self.cache, "reconcile_journal", None)
        if reconcile is None:
            return None
        report = reconcile(cluster_binds, cluster_evicts)
        if report is not None and report.replayed:
            log.warning("journal reconciliation replayed %d unacked "
                        "intents: %s", report.replayed, report.as_dict())
        return report

    def _backoff(self, cap: float) -> float:
        """Exponential backoff with jitter for the current consecutive
        failure count (>= 1), capped at ``cap``."""
        n = max(self.consecutive_failures, 1)
        delay = min(self.backoff_base * (2 ** (n - 1)), cap)
        return delay * (1.0 + self._rng.uniform(0.0, self.backoff_jitter))

    def run(self) -> None:
        """wait.Until(runOnce, period) (scheduler.go:81-88), with the
        crash-loop guard: a failed cycle increments the consecutive
        failure count, flips health to degraded and waits a jittered
        exponential backoff instead of the schedule period; a clean cycle
        resets both. The backoff cap depends on the blast radius: an
        exception ESCAPING run_once (snapshot/session machinery — nothing
        scheduled) backs off up to backoff_max, while isolated per-action
        faults (the rest of the pipeline ran fine) cap near the schedule
        period — one chronically failing action must not throttle healthy
        actions and the resync retries to crash-loop cadence."""
        if not self._reconciled:
            try:
                self.startup_reconcile()
            except Exception:
                log.exception("startup journal reconciliation failed; "
                              "continuing (side effects may retry)")
        while not self._stop.is_set():
            cycle_start = time.perf_counter()
            cycle_fault = False
            try:
                errors = self.run_once()
            except Exception as exc:
                log.exception("scheduling cycle failed outside the action "
                              "pipeline")
                errors = [("cycle", exc)]
                cycle_fault = True
            if errors:
                self.consecutive_failures += 1
                metrics.set_health(metrics.DEGRADED,
                                   self.consecutive_failures)
                cap = self.backoff_max if cycle_fault else \
                    max(self.schedule_period, self.backoff_base)
                self.clock.sleep(self._backoff(cap))
                continue
            if self.consecutive_failures:
                self.consecutive_failures = 0
            metrics.set_health(metrics.HEALTHY, 0)
            remaining = self.schedule_period - (time.perf_counter() - cycle_start)
            if remaining > 0:
                self.clock.sleep(remaining)

    def prewarm(self, configs=None) -> int:
        """Pre-trace/compile the configured allocate solver at the shape
        buckets the steady-state loop will hit, so cold-bucket XLA
        compiles (a 6.5 s stall when a fresh arrival-batch bucket first
        appears mid-churn) pay at startup instead of inside a scheduling
        cycle.

        ``configs`` is an iterable of ``(tasks, jobs)`` shape hints — the
        pending-task count and the number of jobs owning them for each
        cycle shape to warm (task counts snap to the engine's pow2
        buckets, so one entry covers its whole bucket). None derives a
        single entry from the cache's current pending set. Engines
        resolve exactly as AllocateAction.execute does (conf
        ``configurations`` override the action default); the callback
        engines compile nothing and return 0. Returns the number of
        shapes warmed."""
        from .framework import close_session, get_action, open_session
        engine = None
        for name in self.conf.actions:
            if name not in ("allocate", "allocate-tpu"):
                continue
            action = get_action(name)
            engine = getattr(action, "engine", None) or "callbacks"
            for c in self.conf.configurations:
                if c.name in (name, "allocate"):
                    engine = c.arguments.get("engine", engine)
            break
        # the preempt walk warms too (its (preemptor, victim-slot) axes
        # bucket pow2 — evict_tpu.prewarm_preempt mirrors the live path)
        preempt_engine = None
        if "preempt" in self.conf.actions:
            action = get_action("preempt")
            preempt_engine = getattr(action, "engine", None) or "callbacks"
            for c in self.conf.configurations:
                if c.name == "preempt":
                    preempt_engine = c.arguments.get("engine",
                                                     preempt_engine)
        if (engine is None or engine.startswith("callbacks")) \
                and preempt_engine not in ("tpu", "tpu-sharded"):
            return 0
        from .actions.allocate import prewarm_shapes
        ssn = open_session(self.cache, self.conf.tiers,
                           self.conf.configurations,
                           time_fn=self.clock.now)
        try:
            return prewarm_shapes(ssn, configs,
                                  engine or "callbacks",
                                  preempt_engine=preempt_engine)
        finally:
            close_session(ssn)

    def run_with_leader_election(self, store, name: str = "vc-scheduler",
                                 **lease_kwargs) -> None:
        """HA entry point (cmd/scheduler/app/server.go:111-141): block until
        this replica holds the lease in the store, then run the loop; losing
        the lease stops it."""
        from .leaderelection import LeaderElector
        self._elector = LeaderElector(
            store, name, on_started_leading=self.run,
            on_stopped_leading=self.stop, **lease_kwargs)
        self.attach_elector(self._elector)
        self._elector.run()

    def start(self) -> threading.Thread:
        thread = threading.Thread(target=self.run, daemon=True,
                                  name="vc-scheduler")
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()
