"""Baseline semantics: grandfathered findings, each with its own
justification.

The baseline is NOT an escape hatch for new violations — it exists for
findings that are deliberate (e.g. a constant-shape solver axis the
bucketing rule cannot see) and records WHY, per finding. Entries without
a non-empty ``justification`` are a hard error; entries that no longer
match any finding are reported as stale so the file shrinks as debt is
paid."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "vlint-baseline.json"


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing justification)."""


@dataclass
class Baseline:
    path: Optional[str] = None
    entries: Dict[Tuple[str, str, str], dict] = field(default_factory=dict)

    def match(self, finding: Finding) -> bool:
        entry = self.entries.get(finding.key())
        if entry is None:
            return False
        entry["_hit"] = True
        return True

    def stale_entries(self) -> List[dict]:
        return [dict(e, _hit=None) for e in self.entries.values()
                if not e.get("_hit")]

    @staticmethod
    def entry_key(entry: dict) -> Tuple[str, str, str]:
        return (entry["rule"], entry["path"], entry.get("symbol", ""))


def load_baseline(path: Optional[str]) -> Baseline:
    if path is None or not os.path.exists(path):
        return Baseline(path=path)
    with open(path, encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as exc:
            raise BaselineError(f"{path}: not valid JSON ({exc})") from exc
    if not isinstance(data, dict) or "findings" not in data:
        raise BaselineError(f"{path}: expected an object with a "
                            f"'findings' array")
    baseline = Baseline(path=path)
    for i, entry in enumerate(data["findings"]):
        for req in ("rule", "path"):
            if not entry.get(req):
                raise BaselineError(
                    f"{path}: findings[{i}] missing required '{req}'")
        if not str(entry.get("justification", "")).strip():
            raise BaselineError(
                f"{path}: findings[{i}] ({entry['rule']} {entry['path']}) "
                f"has no justification — every grandfathered finding must "
                f"say why it is allowed to stay")
        baseline.entries[Baseline.entry_key(entry)] = dict(entry)
    return baseline


def write_baseline(
        path: str, findings: List[Finding],
        justifications: Optional[Dict[Tuple[str, str, str], str]] = None,
        ) -> None:
    """--update-baseline: rewrite the file from the current findings.
    ``justifications`` maps ``finding.key()`` to the justification to
    keep (the CLI passes the prior baseline's, so re-running never
    erases a written reason); findings without one get a placeholder
    the loader will accept but reviewers must replace."""
    justifications = justifications or {}
    payload = {
        "version": BASELINE_VERSION,
        "findings": [
            {"rule": f.rule, "path": f.path, "symbol": f.symbol,
             "message": f.message,
             "justification": justifications.get(f.key())
             or "TODO: justify or fix"}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
