"""vlint engine: module model, suppressions, and the intra-package
call graph.

The graph serves two precision tiers: ``one_hop`` (the original funnel
rules — a witness may live in a direct caller/callee) and the cached
TRANSITIVE closures ``reach``/``transitive_callers``/``transitive_callees``
that the dataflow rules (VT010-VT014, and the re-pointed VT006) use to
ask "is a witness anywhere on the reachable path" and "which
obs_trace.span contexts can this function run under".

Everything here is stdlib ``ast`` — the analyzer never imports the code
it checks, so it runs in CI without jax or a device present.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

PACKAGE = "volcano_tpu"

# ``# vlint: disable=VT001,VT002 -- why this is fine`` — the justification
# after ``--`` is REQUIRED; a disable without one is itself reported
# (VT000) and suppresses nothing.
_SUPPRESS_RE = re.compile(
    r"#\s*vlint:\s*disable=(?P<rules>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"(?P<just>\s*--\s*(?P<text>.*))?")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str            # "VT001"
    path: str            # repo-relative posix path
    line: int            # 1-based
    col: int             # 0-based
    symbol: str          # dotted function/method ("" for module level)
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift, (rule, path, symbol)
        does not."""
        return (self.rule, self.path, self.symbol)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message}


@dataclass
class Suppression:
    line: int                 # line the suppression APPLIES to
    rules: Set[str]
    justification: str
    comment_line: int         # line the comment physically sits on
    used: bool = False


@dataclass
class FunctionInfo:
    """One function/method definition with the pre-computed facts rules
    share: which simple names it calls and where it sits."""

    module: "ModuleInfo"
    qualname: str                       # "SchedulerCache.bind" / "bind"
    node: ast.AST                       # FunctionDef | AsyncFunctionDef
    cls: Optional[str]                  # owning class name or None
    called_names: Set[str] = field(default_factory=set)
    # subset of called_names eligible as CALL-GRAPH EDGES: bare calls and
    # single-receiver method calls (``helper()``, ``self.helper()``,
    # ``cache.evict()``). ``self.evictor.evict()`` is NOT linkable — the
    # receiver is a nested attribute (an executor object), and linking it
    # to a same-named local def would let a witness-carrying caller
    # excuse a function it never actually calls.
    linkable_calls: Set[str] = field(default_factory=set)
    # callee simple name -> union of obs_trace.span("...") names lexically
    # enclosing a call site of that callee in THIS function (the edge
    # annotation span-context propagation rides; see CallGraph.span_context)
    call_spans: Dict[str, Set[str]] = field(default_factory=dict)
    # span names this function opens anywhere in its body
    spans_opened: Set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        return self.node.name

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<fn {self.module.path}::{self.qualname}>"


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, None for anything fancier."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def span_call_name(node: ast.AST) -> Optional[str]:
    """The literal name of an ``obs_trace.span("X", ...)`` / ``span("X")``
    call (the flight-recorder context manager, PR 5), else None. Only
    string-constant names count — a computed span name cannot anchor an
    allowlist."""
    if not isinstance(node, ast.Call) or not node.args:
        return None
    dotted = dotted_name(node.func)
    if dotted is None or not (dotted == "span" or dotted.endswith(".span")):
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return first.value
    return None


def enclosing_span_names(fn: "FunctionInfo", line: int) -> Set[str]:
    """Span names of every ``with ...span("X")`` block in ``fn`` whose
    lexical extent covers ``line`` — the direct (same-function) half of
    the span-context question; CallGraph.span_context answers the
    inherited half."""
    out: Set[str] = set()
    for w in ast.walk(fn.node):
        if not isinstance(w, ast.With):
            continue
        if not (w.lineno <= line <= getattr(w, "end_lineno", w.lineno)):
            continue
        for item in w.items:
            name = span_call_name(item.context_expr)
            if name is not None:
                out.add(name)
    return out


class ModuleInfo:
    """Parsed module + the lexical facts rules need."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.suppressions: List[Suppression] = []
        self.invalid_suppressions: List[Finding] = []
        self._parse_suppressions()
        # import alias maps: local name -> imported module ("np" ->
        # "numpy"), and from-imports: local name -> "module.attr"
        self.import_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}
        self._collect_imports()
        self.functions: List[FunctionInfo] = []
        self._collect_functions()

    # -- suppressions -------------------------------------------------------

    def _parse_suppressions(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                StringIO(self.source).readline))
        except tokenize.TokenError:  # pragma: no cover - defensive
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            comment_line = tok.start[0]
            rules = {r.strip() for r in m.group("rules").split(",")}
            text = (m.group("text") or "").strip()
            # a comment alone on its line applies to the next line;
            # a trailing comment applies to its own line
            line_src = self.lines[comment_line - 1].strip() \
                if comment_line <= len(self.lines) else ""
            applies = comment_line + 1 if line_src.startswith("#") \
                else comment_line
            if not text:
                self.invalid_suppressions.append(Finding(
                    rule="VT000", path=self.path, line=comment_line, col=0,
                    symbol="",
                    message="vlint suppression without a justification: "
                            "write '# vlint: disable=%s -- <why>'"
                            % ",".join(sorted(rules))))
                continue
            self.suppressions.append(Suppression(
                line=applies, rules=rules, justification=text,
                comment_line=comment_line))

    def suppressed(self, rule: str, line: int) -> bool:
        for sup in self.suppressions:
            if sup.line == line and rule in sup.rules:
                sup.used = True
                return True
        return False

    # -- imports ------------------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"

    def resolve_call(self, node: ast.Call) -> Optional[str]:
        """Canonical dotted target of a call, with the first component
        resolved through this module's imports: ``_time.time()`` ->
        ``time.time``; ``datetime.now()`` (from-import) ->
        ``datetime.datetime.now``. None when the callee is not a plain
        name/attribute chain."""
        dotted = dotted_name(node.func)
        if dotted is None:
            return None
        parts = dotted.split(".")
        head = parts[0]
        if head in self.import_aliases:
            parts[0] = self.import_aliases[head]
        elif head in self.from_imports:
            parts[0] = self.from_imports[head]
        return ".".join(parts)

    # -- functions ----------------------------------------------------------

    def _collect_functions(self) -> None:
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: List[str] = []
                self.cls: List[str] = []

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                self.cls.append(node.name)
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()
                self.cls.pop()

            def _fn(self, node) -> None:
                qual = ".".join(self.stack + [node.name])
                info = FunctionInfo(
                    module=mod, qualname=qual, node=node,
                    cls=self.cls[-1] if self.cls else None)

                def collect(n: ast.AST, spans: Tuple[str, ...]) -> None:
                    # recursive walk carrying the enclosing-span stack so
                    # call edges are annotated with the span context they
                    # fire under (ast.walk would lose the nesting)
                    if isinstance(n, ast.Call):
                        name = None
                        if isinstance(n.func, ast.Name):
                            name = n.func.id
                            info.called_names.add(name)
                            info.linkable_calls.add(name)
                        elif isinstance(n.func, ast.Attribute):
                            name = n.func.attr
                            info.called_names.add(name)
                            if isinstance(n.func.value, ast.Name):
                                info.linkable_calls.add(name)
                        if name is not None:
                            info.call_spans.setdefault(
                                name, set()).update(spans)
                    if isinstance(n, ast.With):
                        opened = [s for item in n.items
                                  if (s := span_call_name(
                                      item.context_expr)) is not None]
                        info.spans_opened.update(opened)
                        inner = spans + tuple(opened)
                        for item in n.items:
                            collect(item.context_expr, spans)
                        for stmt in n.body:
                            collect(stmt, inner)
                        return
                    for child in ast.iter_child_nodes(n):
                        collect(child, spans)

                for dec in node.decorator_list:
                    collect(dec, ())
                for default in (list(node.args.defaults)
                                + [d for d in node.args.kw_defaults if d]):
                    collect(default, ())
                for stmt in node.body:
                    collect(stmt, ())
                mod.functions.append(info)
                self.stack.append(node.name)
                self.generic_visit(node)
                self.stack.pop()

            visit_FunctionDef = _fn
            visit_AsyncFunctionDef = _fn

        V().visit(self.tree)

    def enclosing_function(self, line: int) -> Optional[FunctionInfo]:
        """Innermost function containing ``line``."""
        best: Optional[FunctionInfo] = None
        for fn in self.functions:
            end = getattr(fn.node, "end_lineno", fn.node.lineno)
            if fn.node.lineno <= line <= end:
                if best is None or fn.node.lineno >= best.node.lineno:
                    best = fn
        return best


class CallGraph:
    """Lightweight intra-package call graph over SIMPLE names: good enough
    for one hop of indirection (a funnel's helper, a helper's funnel).
    Edges come from ``linkable_calls`` — bare calls and single-receiver
    method calls. Rules use the graph to EXCUSE code (a callee or caller
    carries the witness), so edge precision matters in one direction
    only: a missing edge can cost a false positive (fixable with a
    justified suppression), while a bogus edge would HIDE a finding —
    which is why ``self.evictor.evict()`` does not link to a local
    ``evict`` def (see FunctionInfo.linkable_calls)."""

    def __init__(self, modules: Iterable[ModuleInfo]):
        self.defs: Dict[str, List[FunctionInfo]] = {}
        self.callers: Dict[str, List[FunctionInfo]] = {}
        for mod in modules:
            for fn in mod.functions:
                self.defs.setdefault(fn.name, []).append(fn)
        for mod in modules:
            for fn in mod.functions:
                for name in fn.linkable_calls:
                    if name in self.defs:
                        self.callers.setdefault(name, []).append(fn)

    def callers_of(self, fn: FunctionInfo) -> List[FunctionInfo]:
        return [c for c in self.callers.get(fn.name, []) if c is not fn]

    def callees_of(self, fn: FunctionInfo) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        for name in fn.linkable_calls:
            for cand in self.defs.get(name, []):
                if cand is not fn:
                    out.append(cand)
        return out

    def one_hop(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Direct callers + direct callees: the neighborhood a funnel
        witness may legitimately live in."""
        return self.callers_of(fn) + self.callees_of(fn)

    # -- transitive closures (the dataflow rules' reach) --------------------

    def _closure(self, fn: FunctionInfo, step, cache: Dict[int, list]
                 ) -> List[FunctionInfo]:
        key = id(fn)
        hit = cache.get(key)
        if hit is not None:
            return hit
        seen: Dict[int, FunctionInfo] = {id(fn): fn}
        frontier = [fn]
        while frontier:
            nxt: List[FunctionInfo] = []
            for f in frontier:
                for other in step(f):
                    if id(other) not in seen:
                        seen[id(other)] = other
                        nxt.append(other)
            frontier = nxt
        out = [f for k, f in seen.items() if k != id(fn)]
        cache[key] = out
        return out

    def transitive_callees(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Everything reachable BY CALLING from ``fn`` (fn excluded),
        cached."""
        if not hasattr(self, "_tc_callees"):
            self._tc_callees: Dict[int, list] = {}
        return self._closure(fn, self.callees_of, self._tc_callees)

    def transitive_callers(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Everything that can REACH ``fn`` by calling (fn excluded),
        cached."""
        if not hasattr(self, "_tc_callers"):
            self._tc_callers: Dict[int, list] = {}
        return self._closure(fn, self.callers_of, self._tc_callers)

    def reach(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Transitive callers + transitive callees: every function on some
        call path THROUGH ``fn``. This is where the dataflow rules look
        for a witness ("the shapes are bucketed somewhere on the reachable
        path") — the transitive generalization of ``one_hop``."""
        out = {id(f): f for f in self.transitive_callers(fn)}
        for f in self.transitive_callees(fn):
            out.setdefault(id(f), f)
        out.pop(id(fn), None)
        return list(out.values())

    def span_context(self, fn: FunctionInfo) -> Set[str]:
        """Union of obs_trace.span names ``fn`` can run under: spans
        lexically wrapping some call site on a path to ``fn``, propagated
        down the call graph to a fixpoint. MAY-analysis by design — a
        function invoked both under ``span("replay")`` and bare reports
        {"replay"}; rules that use contexts to EXCUSE findings (VT010's
        readback-span allowlist) accept that bias and say so in their
        docs. Context only propagates through UNAMBIGUOUS simple names
        (exactly one def in the package): a shared name like ``execute``
        would smear one action's span context over every action and
        EXCUSE real findings — the direction this graph must not err in.
        The whole map is computed once and cached."""
        ctx_map = getattr(self, "_span_ctx", None)
        if ctx_map is None:
            ctx_map = {id(f): set() for fns in self.defs.values()
                       for f in fns}
            changed = True
            while changed:
                changed = False
                for fns in self.defs.values():
                    for g in fns:
                        base = ctx_map[id(g)]
                        for name in g.linkable_calls:
                            targets = self.defs.get(name)
                            if not targets or len(targets) > 1:
                                continue
                            contrib = base | g.call_spans.get(name, set())
                            if not contrib:
                                continue
                            for callee in targets:
                                if callee is g:
                                    continue
                                cur = ctx_map[id(callee)]
                                if not contrib <= cur:
                                    cur.update(contrib)
                                    changed = True
            self._span_ctx = ctx_map
        return ctx_map.get(id(fn), set())


class AnalysisContext:
    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.by_path: Dict[str, ModuleInfo] = {m.path: m for m in modules}
        self.graph = CallGraph(modules)

    def witness_in_scope(self, fn: FunctionInfo, witness_names: Set[str],
                         hop: bool = True) -> bool:
        """Does ``fn`` call one of ``witness_names``, or (one hop) does a
        direct caller or callee?"""
        if fn.called_names & witness_names:
            return True
        if not hop:
            return False
        for other in self.graph.one_hop(fn):
            if other.called_names & witness_names:
                return True
        return False

    def witness_in_reach(self, fn: FunctionInfo,
                         witness_names: Set[str]) -> bool:
        """Transitive version of ``witness_in_scope``: does ``fn``, any
        transitive caller, or any transitive callee call one of
        ``witness_names``? The dataflow rules' reach semantics — "the
        shapes are routed through a bucket helper SOMEWHERE on the
        reachable path"."""
        if fn.called_names & witness_names:
            return True
        for other in self.graph.reach(fn):
            if other.called_names & witness_names:
                return True
        return False


def normalize_path(path: str) -> str:
    """Repo-relative posix path starting at the package directory, so
    findings and baselines are stable regardless of invocation cwd."""
    posix = path.replace(os.sep, "/")
    marker = f"{PACKAGE}/"
    idx = posix.rfind(marker)
    if idx >= 0:
        return posix[idx:]
    return posix


def iter_python_files(paths: Iterable[str]) -> Iterator[Tuple[str, str]]:
    """(normalized_path, absolute_path) for every .py under ``paths``."""
    for path in paths:
        if os.path.isfile(path):
            yield normalize_path(path), os.path.abspath(path)
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for name in sorted(files):
                if name.endswith(".py"):
                    full = os.path.join(root, name)
                    yield normalize_path(full), os.path.abspath(full)


def analyze_sources(sources: Dict[str, str], rules=None
                    ) -> Tuple[List[Finding], List[Finding],
                               AnalysisContext]:
    """Run ``rules`` (default: all) over in-memory ``{path: source}``.
    Returns (findings, invalid_suppressions, context); findings are
    post-suppression, sorted by location. This is the testing entry point
    — fixture tests and the re-broken-historical-bug regressions feed
    mutated sources through here without touching the tree."""
    from .rules import ALL_RULES
    rules = ALL_RULES if rules is None else rules
    modules: List[ModuleInfo] = []
    errors: List[Finding] = []
    for path, src in sorted(sources.items()):
        norm = normalize_path(path)
        try:
            modules.append(ModuleInfo(norm, src))
        except SyntaxError as exc:
            errors.append(Finding(
                rule="VT000", path=norm, line=exc.lineno or 0, col=0,
                symbol="", message=f"syntax error: {exc.msg}"))
    ctx = AnalysisContext(modules)
    findings: List[Finding] = list(errors)
    invalid: List[Finding] = []
    for mod in modules:
        invalid.extend(mod.invalid_suppressions)
    for rule in rules:
        for mod in modules:
            if not rule.applies_to(mod.path):
                continue
            for f in rule.check(mod, ctx):
                if not mod.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    invalid.sort(key=lambda f: (f.path, f.line))
    return findings, invalid, ctx


def analyze_paths(paths: Iterable[str], rules=None
                  ) -> Tuple[List[Finding], List[Finding], AnalysisContext]:
    sources: Dict[str, str] = {}
    for norm, full in iter_python_files(paths):
        with open(full, encoding="utf-8") as f:
            sources[norm] = f.read()
    return analyze_sources(sources, rules=rules)
