"""vlint reporters: text for humans, JSON for CI.

The JSON schema (version 1) is a contract the tests pin:

{
  "version": 1,
  "findings":             [{rule, path, line, col, symbol, message}],
  "invalid_suppressions": [{rule, path, line, col, symbol, message}],
  "baselined":            [{rule, path, line, col, symbol, message}],
  "stale_baseline":       [{rule, path, symbol, message, justification}],
  "counts": {"findings": N, "baselined": N, "invalid_suppressions": N,
             "stale_baseline": N},
  "exit_code": 0|1
}
"""

from __future__ import annotations

import json
from typing import List

from .baseline import Baseline
from .core import Finding


def split_baselined(findings: List[Finding], baseline: Baseline):
    """(live, baselined) — a finding matching a justified baseline entry
    is reported separately and does not gate."""
    live: List[Finding] = []
    grandfathered: List[Finding] = []
    for f in findings:
        (grandfathered if baseline.match(f) else live).append(f)
    return live, grandfathered


def exit_code(live: List[Finding], invalid: List[Finding]) -> int:
    return 1 if (live or invalid) else 0


def text_report(live: List[Finding], invalid: List[Finding],
                baselined: List[Finding], baseline: Baseline) -> str:
    lines: List[str] = []
    for f in invalid:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
    for f in live:
        sym = f" [{f.symbol}]" if f.symbol else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}{sym} "
                     f"{f.message}")
    stale = baseline.stale_entries()
    for e in stale:
        lines.append(f"note: stale baseline entry {e['rule']} {e['path']} "
                     f"[{e.get('symbol', '')}] — the finding is gone; "
                     f"remove it from {baseline.path}")
    n = len(live) + len(invalid)
    detail = (f"{n} blocking finding(s), {len(baselined)} baselined, "
              f"{len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'}")
    lines.append(f"vlint: {detail}" if n else f"vlint: clean ({detail})")
    return "\n".join(lines)


SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
# rule help anchors: docs/static-analysis.md carries an explicit
# <a id="vtxxx"></a> per rule so the URI survives heading rewording
DOC_URI = "docs/static-analysis.md"


def sarif_report(live: List[Finding], invalid: List[Finding],
                 baselined: List[Finding]) -> str:
    """SARIF 2.1.0 (``--format sarif``): one run, the full rule catalog
    with help URIs into docs/static-analysis.md, one result per finding.
    Live findings and invalid suppressions are ``error``; baselined
    findings are emitted as suppressed ``note`` results so diff
    annotation shows the debt without failing the check."""
    from .rules import ALL_RULES
    rule_ids = [r.id for r in ALL_RULES] + ["VT000"]
    rules_meta = [
        {
            "id": r.id,
            "name": r.name or r.id,
            "shortDescription": {"text": r.contract or r.id},
            "fullDescription": {
                "text": (r.__doc__ or r.contract or r.id).strip()},
            "helpUri": f"{DOC_URI}#{r.id.lower()}",
            "defaultConfiguration": {"level": "error"},
        }
        for r in ALL_RULES
    ] + [{
        "id": "VT000",
        "name": "analyzer-error",
        "shortDescription": {"text": "vlint analyzer error / invalid "
                                     "suppression"},
        "helpUri": f"{DOC_URI}#vt000",
        "defaultConfiguration": {"level": "error"},
    }]
    index = {rid: i for i, rid in enumerate(rule_ids)}

    def result(f: Finding, level: str, suppressed: bool) -> dict:
        out = {
            "ruleId": f.rule,
            "ruleIndex": index.get(f.rule, len(rule_ids) - 1),
            "level": level,
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "%SRCROOT%"},
                    "region": {"startLine": max(f.line, 1),
                               "startColumn": f.col + 1},
                },
            }],
        }
        if suppressed:
            out["suppressions"] = [{
                "kind": "external",
                "justification": "grandfathered in vlint-baseline.json "
                                 "(entry carries its own justification)",
            }]
        return out

    payload = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "vlint",
                "informationUri": DOC_URI,
                "rules": rules_meta,
            }},
            "results": (
                [result(f, "error", False) for f in invalid]
                + [result(f, "error", False) for f in live]
                + [result(f, "note", True) for f in baselined]),
        }],
    }
    return json.dumps(payload, indent=2, sort_keys=True)


def json_report(live: List[Finding], invalid: List[Finding],
                baselined: List[Finding], baseline: Baseline) -> str:
    payload = {
        "version": 1,
        "findings": [f.as_dict() for f in live],
        "invalid_suppressions": [f.as_dict() for f in invalid],
        "baselined": [f.as_dict() for f in baselined],
        "stale_baseline": [
            {k: v for k, v in e.items() if not k.startswith("_")}
            for e in baseline.stale_entries()],
        "counts": {
            "findings": len(live),
            "invalid_suppressions": len(invalid),
            "baselined": len(baselined),
            "stale_baseline": len(baseline.stale_entries()),
        },
        "exit_code": exit_code(live, invalid),
    }
    return json.dumps(payload, indent=2, sort_keys=True)
