"""vlint reporters: text for humans, JSON for CI.

The JSON schema (version 1) is a contract the tests pin:

{
  "version": 1,
  "findings":             [{rule, path, line, col, symbol, message}],
  "invalid_suppressions": [{rule, path, line, col, symbol, message}],
  "baselined":            [{rule, path, line, col, symbol, message}],
  "stale_baseline":       [{rule, path, symbol, message, justification}],
  "counts": {"findings": N, "baselined": N, "invalid_suppressions": N,
             "stale_baseline": N},
  "exit_code": 0|1
}
"""

from __future__ import annotations

import json
from typing import List

from .baseline import Baseline
from .core import Finding


def split_baselined(findings: List[Finding], baseline: Baseline):
    """(live, baselined) — a finding matching a justified baseline entry
    is reported separately and does not gate."""
    live: List[Finding] = []
    grandfathered: List[Finding] = []
    for f in findings:
        (grandfathered if baseline.match(f) else live).append(f)
    return live, grandfathered


def exit_code(live: List[Finding], invalid: List[Finding]) -> int:
    return 1 if (live or invalid) else 0


def text_report(live: List[Finding], invalid: List[Finding],
                baselined: List[Finding], baseline: Baseline) -> str:
    lines: List[str] = []
    for f in invalid:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}")
    for f in live:
        sym = f" [{f.symbol}]" if f.symbol else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}{sym} "
                     f"{f.message}")
    stale = baseline.stale_entries()
    for e in stale:
        lines.append(f"note: stale baseline entry {e['rule']} {e['path']} "
                     f"[{e.get('symbol', '')}] — the finding is gone; "
                     f"remove it from {baseline.path}")
    n = len(live) + len(invalid)
    detail = (f"{n} blocking finding(s), {len(baselined)} baselined, "
              f"{len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'}")
    lines.append(f"vlint: {detail}" if n else f"vlint: clean ({detail})")
    return "\n".join(lines)


def json_report(live: List[Finding], invalid: List[Finding],
                baselined: List[Finding], baseline: Baseline) -> str:
    payload = {
        "version": 1,
        "findings": [f.as_dict() for f in live],
        "invalid_suppressions": [f.as_dict() for f in invalid],
        "baselined": [f.as_dict() for f in baselined],
        "stale_baseline": [
            {k: v for k, v in e.items() if not k.startswith("_")}
            for e in baseline.stale_entries()],
        "counts": {
            "findings": len(live),
            "invalid_suppressions": len(invalid),
            "baselined": len(baselined),
            "stale_baseline": len(baseline.stale_entries()),
        },
        "exit_code": exit_code(live, invalid),
    }
    return json.dumps(payload, indent=2, sort_keys=True)
