"""vlint: contract-aware static analysis for the volcano_tpu codebase.

The scheduler's correctness rests on conventions that only runtime soaks
catch when broken — the dirty-set witness (docs/performance.md), the
journaled bind/evict funnels (docs/robustness.md), injectable clocks and
seeded RNGs for byte-determinism (docs/simulation.md), SimKill tunneling,
pow2 shape bucketing, and lock discipline in the shared-state modules.
``vlint`` turns each of those conventions into a mechanical check over
the package's ASTs (stdlib ``ast`` only, no new runtime deps):

- VT001  cache-state mutation without a dirty-set/mutation-witness mark
- VT002  raw wall clock (time.time/sleep/monotonic, datetime.now) in
         scheduler-path code outside the sanctioned clock implementations
- VT003  unseeded module-level RNG draws in decision paths
- VT004  bind/evict executor invocation outside the journaled funnels
- VT005  exception handlers that would swallow SimKill (BaseException)
- VT006  jitted solver invocations whose shapes skip pow2 bucketing
- VT007  shared-state writes outside a held lock in native/metrics/obs

Run it: ``python -m volcano_tpu.analysis volcano_tpu/`` (or the ``vlint``
console script). Findings are suppressible per line with
``# vlint: disable=VTxxx -- justification`` (the justification text is
required) and grandfathered findings live in the checked-in
``vlint-baseline.json``, each entry carrying its own justification.
See docs/static-analysis.md for the rule catalog and how to add a rule.
"""

from __future__ import annotations

from .core import (AnalysisContext, Finding, analyze_paths, analyze_sources,
                   iter_python_files)
from .rules import ALL_RULES, rule_by_id
from .baseline import Baseline, load_baseline
from .report import json_report, text_report

__all__ = [
    "ALL_RULES", "AnalysisContext", "Baseline", "Finding", "analyze_paths",
    "analyze_sources", "iter_python_files", "json_report", "load_baseline",
    "rule_by_id", "text_report",
]
