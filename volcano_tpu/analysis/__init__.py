"""vlint: contract-aware static analysis for the volcano_tpu codebase.

The scheduler's correctness rests on conventions that only runtime soaks
catch when broken — the dirty-set witness (docs/performance.md), the
journaled bind/evict funnels (docs/robustness.md), injectable clocks and
seeded RNGs for byte-determinism (docs/simulation.md), SimKill tunneling,
pow2 shape bucketing, and lock discipline in the shared-state modules.
``vlint`` turns each of those conventions into a mechanical check over
the package's ASTs (stdlib ``ast`` only, no new runtime deps):

- VT001  cache-state mutation without a dirty-set/mutation-witness mark
- VT002  raw wall clock (time.time/sleep/monotonic, datetime.now) in
         scheduler-path code outside the sanctioned clock implementations
- VT003  unseeded module-level RNG draws in decision paths
- VT004  bind/evict executor invocation outside the journaled funnels
- VT005  exception handlers that would swallow SimKill (BaseException)
- VT006  jitted solver invocations whose shapes skip pow2 bucketing
         (transitive-reach witness since PR 11)
- VT007  shared-state writes outside a held lock in native/metrics/obs
- VT008  executor-effecting calls without a fencing-epoch stamp (HA)
- VT009  partition-ownership writes outside the reserve/transfer funnel

Since PR 11 the analyzer is also a DATAFLOW engine (``dataflow.py``): an
interprocedural taint lattice tracks device arrays, tracers and
session-scoped values through assignments, calls, returns and
comprehensions, powering five more rules:

- VT010  implicit host sync on a device value outside an allowlisted
         replay/readback span (the async-overlap worklist; also
         ``vlint --sync-inventory``)
- VT011  Python if/while/assert on a traced value inside a jitted fn
- VT012  dataflow-detected jit invocations missing the bucket witness
- VT013  weak-dtype / bare-literal operands feeding jitted solvers
- VT014  session-scoped values stored past close_session's lifetime

Run it: ``python -m volcano_tpu.analysis volcano_tpu/`` (or the ``vlint``
console script); ``--dataflow`` runs just the taint rules, ``--diff
BASE`` restricts to changed functions, ``--format sarif`` emits SARIF
2.1.0, ``--explain VTxxx`` prints a rule's contract + minimal trigger.
Findings are suppressible per line with
``# vlint: disable=VTxxx -- justification`` (the justification text is
required) and grandfathered findings live in the checked-in
``vlint-baseline.json``, each entry carrying its own justification.
See docs/static-analysis.md for the rule catalog and how to add a rule.
"""

from __future__ import annotations

from .core import (AnalysisContext, Finding, analyze_paths, analyze_sources,
                   iter_python_files)
from .rules import ALL_RULES, rule_by_id
from .baseline import Baseline, load_baseline
from .report import json_report, sarif_report, text_report

__all__ = [
    "ALL_RULES", "AnalysisContext", "Baseline", "Finding", "analyze_paths",
    "analyze_sources", "iter_python_files", "json_report", "load_baseline",
    "rule_by_id", "sarif_report", "text_report",
]
