"""The vlint rule catalog: one class per contract.

Each rule names the PR that established its contract (docs/
static-analysis.md carries the full catalog). Rules are deliberately
scoped to the modules where the contract applies — a wall-clock read in
the CLI is fine; the same read inside a plugin's decision callback breaks
sim byte-determinism.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import (AnalysisContext, Finding, FunctionInfo, ModuleInfo,
                   dotted_name, enclosing_span_names)


def _in_scope(path: str, prefixes: Sequence[str]) -> bool:
    return any(path == p or (p.endswith("/") and path.startswith(p))
               for p in prefixes)


class Rule:
    id: str = "VT000"
    name: str = ""
    contract: str = ""
    scope: Sequence[str] = ()
    exclude: Sequence[str] = ()
    example: str = ""          # minimal trigger snippet (vlint --explain)

    def applies_to(self, path: str) -> bool:
        if _in_scope(path, self.exclude):
            return False
        if not self.scope:
            return True
        return _in_scope(path, self.scope)

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> List[Finding]:  # pragma: no cover - abstract
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str
                ) -> Finding:
        fn = mod.enclosing_function(node.lineno)
        return Finding(rule=self.id, path=mod.path, line=node.lineno,
                       col=getattr(node, "col_offset", 0),
                       symbol=fn.qualname if fn else "", message=message)


# ---------------------------------------------------------------------------
# VT001 — dirty-set witness (PR 3, docs/performance.md)
# ---------------------------------------------------------------------------

class DirtyWitnessRule(Rule):
    """Every cluster-state mutation must mark the dirty set (or set the
    ``_touched`` mutation witness) on the path — a missed mark makes
    clone-on-dirty serve a stale placement input, silently. The witness
    may live one call-graph hop away (a funnel's helper, a helper's
    funnel)."""

    id = "VT001"
    name = "dirty-witness"
    contract = ("cache-state mutation without a mark_*_dirty/_touched "
                "witness on the path (PR 3 incremental snapshots)")
    scope = ("volcano_tpu/cache/cache.py",
             "volcano_tpu/cache/store_wiring.py",
             "volcano_tpu/sim/runner.py")

    MUTATOR_CALLS = {"add_task", "remove_task", "update_task",
                     "add_task_info", "delete_task_info",
                     "update_task_status"}
    MUTATED_ATTRS = {"status", "node_name"}
    STATE_DICTS = {"nodes", "jobs", "queues"}
    WITNESS_CALLS = {"mark_node_dirty", "mark_job_dirty", "mark_queue_dirty",
                     "mark_all_dirty", "_mark_task_dirty"}
    DIRTY_SETS = {"_dirty_nodes", "_dirty_jobs", "_dirty_queues",
                  "_tensor_dirty"}

    def _has_witness(self, fn: FunctionInfo) -> bool:
        if fn.called_names & self.WITNESS_CALLS:
            return True
        for node in ast.walk(fn.node):
            # self._dirty_nodes.add(...) / _tensor_dirty.add(...)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "add":
                recv = dotted_name(node.func.value) or ""
                if recv.split(".")[-1] in self.DIRTY_SETS:
                    return True
            # self._dirty_all = True / obj._touched = True
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and tgt.attr in ("_dirty_all", "_touched"):
                        return True
        return False

    def _mutations(self, fn: FunctionInfo) -> List[Tuple[ast.AST, str]]:
        out: List[Tuple[ast.AST, str]] = []
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self.MUTATOR_CALLS:
                recv = dotted_name(node.func.value) or "<expr>"
                out.append((node, f"{recv}.{node.func.attr}(...)"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) \
                            and tgt.attr in self.MUTATED_ATTRS:
                        recv = dotted_name(tgt.value) or "<expr>"
                        if recv == "self":
                            continue
                        out.append((node, f"{recv}.{tgt.attr} = ..."))
                    # self.nodes[k] = v / del-by-pop handled via calls
                    elif isinstance(tgt, ast.Subscript) \
                            and isinstance(tgt.value, ast.Attribute) \
                            and tgt.value.attr in self.STATE_DICTS \
                            and dotted_name(tgt.value.value) == "self":
                        out.append((node,
                                    f"self.{tgt.value.attr}[...] = ..."))
        return out

    # node-mirror ops that keep the node's task clone + accounting in
    # step with a job-side status flip (the evict-retry mirror bug: the
    # retry success path updated only the JOB status; the node mirror
    # holds a CLONE, so a phantom RUNNING task kept occupying idle)
    MIRROR_CALLS = {"add_task", "remove_task", "update_task"}

    def _enclosing_block(self, fn: FunctionInfo,
                         target: ast.AST) -> Optional[List[ast.stmt]]:
        """Deepest statement list whose subtree contains ``target``."""
        best: Optional[List[ast.stmt]] = None

        def visit(body: List[ast.stmt]) -> None:
            nonlocal best
            for stmt in body:
                found = any(sub is target for sub in ast.walk(stmt))
                if found:
                    best = body
                for attr in ("body", "orelse", "finalbody"):
                    visit(getattr(stmt, attr, []) or [])
                for h in getattr(stmt, "handlers", []) or []:
                    visit(h.body)

        visit(fn.node.body)
        return best

    def _block_has_mirror(self, block: List[ast.stmt]) -> bool:
        for stmt in block:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in self.MIRROR_CALLS:
                    return True
                # node.tasks[uid] = clone (the bind_batch agg fast path)
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript) \
                                and isinstance(tgt.value, ast.Attribute) \
                                and tgt.value.attr == "tasks":
                            return True
        return False

    def _mirror_findings(self, mod: ModuleInfo) -> List[Finding]:
        """cache/cache.py only: a job-side status flip must keep the node
        mirror in step within the same statement block."""
        out: List[Finding] = []
        if not mod.path.endswith("cache/cache.py"):
            return out
        for fn in mod.functions:
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "update_task_status"
                        and dotted_name(node.func.value) != "self"):
                    continue
                block = self._enclosing_block(fn, node)
                if block is not None and self._block_has_mirror(block):
                    continue
                out.append(self.finding(
                    mod, node,
                    f"job-side update_task_status in {fn.qualname} with no "
                    f"node-mirror maintenance (add/remove/update_task) in "
                    f"the same block; the node holds a CLONE — its "
                    f"accounting drifts and preempt sees phantom tasks "
                    f"(the PR 4 evict-retry mirror bug)"))
        return out

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = list(self._mirror_findings(mod))
        for fn in mod.functions:
            muts = self._mutations(fn)
            if not muts:
                continue
            if self._has_witness(fn):
                continue
            # one hop: a direct caller or callee carrying the witness
            # excuses the function (e.g. _release_numa is only reached
            # from funnels that already marked the node dirty). Defs NAMED
            # like mutator methods are excluded from the excuse set: the
            # graph links ``job.update_task_status(...)`` to any local def
            # of that name, and a well-behaved mutator elsewhere must not
            # vouch for THIS object's unmarked mutation.
            neighborhood = [o for o in ctx.graph.one_hop(fn)
                            if o.name not in self.MUTATOR_CALLS]
            if any(self._has_witness(o) for o in neighborhood):
                continue
            node, desc = muts[0]
            findings.append(self.finding(
                mod, node,
                f"cluster-state mutation ({desc}) in {fn.qualname} with no "
                f"mark_*_dirty/_touched witness in the function or one "
                f"call-graph hop; a reused snapshot clone will serve this "
                f"mutation stale (docs/performance.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT002 — injectable clock (PR 2, docs/simulation.md)
# ---------------------------------------------------------------------------

class RawClockRule(Rule):
    """Scheduler-path code must go through the injectable clock
    (Scheduler.clock / Session.now() / a ``time_fn`` parameter) so the
    simulator can pin virtual time. Only the sanctioned clock
    implementations may read the wall clock. References passed as
    defaults (``time_fn=time.monotonic``) are the injection convention
    and are not flagged — only calls are."""

    id = "VT002"
    name = "raw-clock"
    contract = ("raw time.time/time.sleep/time.monotonic/datetime.now "
                "outside the WallClock/VirtualClock implementations "
                "(PR 2 injectable clock)")
    scope = ("volcano_tpu/scheduler.py", "volcano_tpu/leaderelection.py",
             "volcano_tpu/framework/", "volcano_tpu/actions/",
             "volcano_tpu/plugins/", "volcano_tpu/cache/",
             "volcano_tpu/sim/", "volcano_tpu/utils/", "volcano_tpu/ops/",
             "volcano_tpu/parallel/", "volcano_tpu/federation/")

    BANNED_TIME = {"time.time", "time.sleep", "time.monotonic"}
    BANNED_DT_SUFFIX = ("datetime.now", "datetime.utcnow", "datetime.today",
                        "date.today")
    # the sanctioned clock implementations: (path, class name)
    ALLOWED_OWNERS = {("volcano_tpu/scheduler.py", "WallClock"),
                      ("volcano_tpu/sim/runner.py", "VirtualClock")}

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve_call(node)
            if resolved is None:
                continue
            banned = resolved in self.BANNED_TIME or \
                resolved.endswith(self.BANNED_DT_SUFFIX)
            if not banned:
                continue
            fn = mod.enclosing_function(node.lineno)
            if fn is not None and (mod.path, fn.cls) in self.ALLOWED_OWNERS:
                continue
            findings.append(self.finding(
                mod, node,
                f"raw clock call {resolved}() in scheduler-path code; "
                f"inject the time source (clock/ssn.now()/time_fn param) "
                f"so sim replay stays byte-deterministic "
                f"(docs/simulation.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT003 — seeded RNGs (PR 2, docs/simulation.md)
# ---------------------------------------------------------------------------

class UnseededRandomRule(Rule):
    """Decision-path randomness must come from a seeded, injectable
    ``random.Random`` instance (or jax PRNG keys) — module-level
    ``random.*`` / ``np.random.*`` draws share hidden global state no
    replay can pin."""

    id = "VT003"
    name = "unseeded-random"
    contract = ("unseeded module-level random/np.random draws in "
                "scheduler/sim decision paths (PR 2 determinism)")
    scope = RawClockRule.scope

    RANDOM_FNS = {"random", "uniform", "choice", "choices", "randint",
                  "randrange", "sample", "shuffle", "gauss", "betavariate",
                  "expovariate", "triangular", "normalvariate",
                  "vonmisesvariate", "paretovariate", "weibullvariate",
                  "getrandbits", "seed"}
    NP_SEEDED_OK = {"default_rng", "RandomState", "Generator",
                    "SeedSequence"}

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve_call(node)
            if resolved is None:
                continue
            parts = resolved.split(".")
            msg = None
            if parts[0] == "random" and len(parts) == 2 \
                    and parts[1] in self.RANDOM_FNS:
                msg = (f"module-level random.{parts[1]}() draws from the "
                       f"hidden global RNG")
            elif parts[0] == "numpy" and len(parts) >= 2 \
                    and parts[1] == "random":
                tail = parts[2] if len(parts) > 2 else ""
                if tail in self.NP_SEEDED_OK:
                    if node.args or node.keywords:
                        continue        # np.random.default_rng(seed) etc.
                    msg = (f"np.random.{tail}() without a seed is "
                           f"entropy-seeded")
                else:
                    msg = (f"np.random.{tail or '<fn>'}() draws from the "
                           f"numpy global RNG")
            if msg is None:
                continue
            findings.append(self.finding(
                mod, node,
                f"{msg}; use an injectable seeded random.Random/"
                f"np.random.Generator instance so decisions replay "
                f"byte-identically (docs/simulation.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT004 — journaled bind/evict funnels (PR 4, docs/robustness.md)
# ---------------------------------------------------------------------------

class JournalFunnelRule(Rule):
    """Bind/evict side effects may only execute through the journaled
    funnels in cache/cache.py: the executor call must have a
    ``_journal_intent`` record on its path (same function or one hop),
    or a crash between the executor and the cache update is
    unreconstructable — the double-bind class of bug the intent journal
    closed."""

    id = "VT004"
    name = "journal-funnel"
    contract = ("bind/evict executor invocation outside the journaled "
                "funnels in cache/cache.py (PR 4 intent journal)")
    # executors.py IS the executor layer; journal.py IS the journal (its
    # reconciler replays already-journaled intents); chaos.py wraps
    # executors to inject faults below the funnels on purpose
    exclude = ("volcano_tpu/cache/executors.py",
               "volcano_tpu/cache/journal.py", "volcano_tpu/chaos.py",
               "volcano_tpu/analysis/")

    EXECUTOR_ATTRS = {"binder", "evictor"}
    EXECUTOR_METHODS = {"bind", "evict"}
    WITNESS = {"_journal_intent"}

    def _is_executor_call(self, node: ast.Call) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in self.EXECUTOR_METHODS:
            return None
        recv = dotted_name(node.func.value)
        if recv is None:
            return None
        last = recv.split(".")[-1]
        if last in self.EXECUTOR_ATTRS:
            return f"{recv}.{node.func.attr}"
        return None

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._is_executor_call(node)
            if target is None:
                continue
            fn = mod.enclosing_function(node.lineno)
            if fn is not None and ctx.witness_in_scope(fn, self.WITNESS):
                continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"executor invocation {target}(...) in {where} without a "
                f"_journal_intent record on the path; binds/evicts must "
                f"flow through the journaled funnels in cache/cache.py "
                f"(docs/robustness.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT008 — fencing-epoch stamp on executor-effecting calls (PR 7 HA)
# ---------------------------------------------------------------------------

class FencingEpochRule(Rule):
    """Executor-effecting bind/evict calls must carry the issuing
    leadership's fencing epoch: a ``fencing_epoch`` read must be on the
    path (same function or one hop — the ``_journal_intent`` funnel
    reads it for every intent it stamps). An unstamped executor call is
    a side effect the fencing gate cannot order against leaderships —
    a deposed leader could replay it after failover (the split-brain
    double-bind the HA control plane closes by construction)."""

    id = "VT008"
    name = "fencing-epoch"
    contract = ("executor-effecting bind/evict invocation without a "
                "fencing_epoch stamp on the path (PR 7 HA fencing, "
                "docs/robustness.md)")
    # same exemptions as VT004: the executor layer itself, the journal's
    # reconciler (replays already-stamped intents), the chaos wrappers
    exclude = ("volcano_tpu/cache/executors.py",
               "volcano_tpu/cache/journal.py", "volcano_tpu/chaos.py",
               "volcano_tpu/analysis/")

    EXECUTOR_ATTRS = {"binder", "evictor"}
    EXECUTOR_METHODS = {"bind", "evict"}
    WITNESS = {"fencing_epoch"}

    def _is_executor_call(self, node: ast.Call) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in self.EXECUTOR_METHODS:
            return None
        recv = dotted_name(node.func.value)
        if recv is None:
            return None
        if recv.split(".")[-1] in self.EXECUTOR_ATTRS:
            return f"{recv}.{node.func.attr}"
        return None

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._is_executor_call(node)
            if target is None:
                continue
            fn = mod.enclosing_function(node.lineno)
            if fn is not None and ctx.witness_in_scope(fn, self.WITNESS):
                continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"executor invocation {target}(...) in {where} without a "
                f"fencing_epoch stamp on the path; executor-effecting "
                f"operations must carry the leader's epoch so a deposed "
                f"leader's writes are rejectable (docs/robustness.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT009 — cross-partition reserve/transfer funnel (PR 9 federation)
# ---------------------------------------------------------------------------

class CrossPartitionFunnelRule(Rule):
    """Partition-ownership writes (moving a node or queue between
    partitions, pinning a node for transfer, opening a queue drain) are
    writes to cluster state another partition owns: they may only happen
    inside the journaled reserve/transfer funnel — a ``_journal_reserve``
    record must be on the path (same function or one hop, VT004-style).
    A bare transfer is capacity that moved with no durable audit trail
    and no epoch stamp: a restarted partition would disagree with the
    live map about who owns what — the federated double-bind
    (docs/federation.md)."""

    id = "VT009"
    name = "cross-partition-funnel"
    contract = ("PartitionMap ownership transfer outside the journaled "
                "reserve/transfer funnel (PR 9 federation, "
                "docs/federation.md)")
    exclude = ("volcano_tpu/analysis/",)

    TRANSFER_METHODS = {"_transfer_node_raw", "_transfer_queue_raw",
                        "_pin_node_raw", "_begin_drain_raw"}
    WITNESS = {"_journal_reserve"}

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in self.TRANSFER_METHODS:
                continue
            recv = dotted_name(node.func.value) or "<expr>"
            fn = mod.enclosing_function(node.lineno)
            if fn is not None:
                # the raw mutators' own defs are not transfers
                if fn.name in self.TRANSFER_METHODS:
                    continue
                if ctx.witness_in_scope(fn, self.WITNESS):
                    continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"partition-ownership write {recv}.{node.func.attr}(...) "
                f"in {where} without a _journal_reserve record on the "
                f"path; cross-partition state moves only through the "
                f"reserve/transfer funnel (docs/federation.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT019 — elastic membership moves through the journaled funnel
# ---------------------------------------------------------------------------

class MembershipFunnelRule(Rule):
    """Partition MEMBERSHIP writes (minting a partition id for a split,
    opening or completing a retirement for a merge) change who may own
    cluster state at all — strictly stronger than a VT009 ownership
    transfer. They may only happen inside the journaled membership
    funnel: a ``_journal_reserve`` control record (``partition_spawn``,
    ``partition_retire_begin``, ``partition_retire``) must be on the
    path, same function or one hop. A bare membership mutation is a
    partition that exists (or vanished) with no durable record — after
    a crash the survivors and the journal disagree about the member
    set, and a job whose queue the phantom partition owned is either
    orphaned or schedulable twice (docs/federation.md membership-change
    protocol)."""

    id = "VT019"
    name = "membership-funnel"
    contract = ("PartitionMap membership mutation (spawn/retire) outside "
                "the journaled membership funnel (elastic federation, "
                "docs/federation.md)")
    exclude = ("volcano_tpu/analysis/",)

    MEMBER_METHODS = {"_spawn_partition_raw", "_begin_retire_raw",
                      "_retire_partition_raw"}
    WITNESS = {"_journal_reserve"}

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in self.MEMBER_METHODS:
                continue
            recv = dotted_name(node.func.value) or "<expr>"
            fn = mod.enclosing_function(node.lineno)
            if fn is not None:
                # the raw mutators' own defs (and store-backed
                # overrides, which CAS-persist then delegate) are the
                # funnel floor, not membership decisions
                if fn.name in self.MEMBER_METHODS:
                    continue
                if ctx.witness_in_scope(fn, self.WITNESS):
                    continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"membership mutation {recv}.{node.func.attr}(...) in "
                f"{where} without a _journal_reserve control record on "
                f"the path; partitions are minted and retired only "
                f"through the journaled membership funnel "
                f"(docs/federation.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT020 — elastic mutations ride journaled+fenced funnels
# ---------------------------------------------------------------------------

class ElasticFunnelRule(Rule):
    """Elastic-gang mutations come in two shapes, and both must leave a
    durable, epoch-stamped control record on the path. (a) Membership
    moves — a grow (``ssn.allocate``) or shrink (``ssn.evict``) issued
    from the elastic stage — need an ``elastic_grow``/``elastic_shrink``
    record beside the bind/evict intent (the ``_journal_elastic``
    witness): after a crash the replayer must distinguish an elastic
    shrink from a genuine preemption, or it restores surplus members a
    scale-down already shed. (b) Lifecycle verbs — rewrites of the
    ``volcano.sh/suspend`` / ``volcano.sh/elastic-desired`` annotations
    — may only happen inside the Command funnel's consume path, which
    journals ``command_applied``/``command_dropped`` (``record_control``
    witness): a bare annotation write is a suspend that never happened
    as far as the journal is concerned (docs/design/elastic-gangs.md
    lifecycle protocol)."""

    id = "VT020"
    name = "elastic-funnel"
    contract = ("elastic grow/shrink or lifecycle-annotation rewrite "
                "outside the journaled+fenced funnel (elastic gangs, "
                "docs/design/elastic-gangs.md)")
    scope = ("volcano_tpu/elastic_gang/",)

    SESSION_MUTATORS = {"evict", "allocate"}
    ANNOTATION_KEYS = {"SUSPEND_ANNOTATION", "ELASTIC_DESIRED_ANNOTATION"}
    WITNESS = {"_journal_elastic", "record_control"}

    @classmethod
    def _elastic_key(cls, node: Optional[ast.AST]) -> bool:
        if isinstance(node, getattr(ast, "Index", ())):  # py<3.9 slices
            node = node.value
        return isinstance(node, ast.Name) and node.id in cls.ANNOTATION_KEYS

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            desc = None
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                recv = dotted_name(node.func.value) or "<expr>"
                if node.func.attr in self.SESSION_MUTATORS:
                    desc = (f"elastic member move "
                            f"{recv}.{node.func.attr}(...)")
                elif node.func.attr == "pop" and node.args \
                        and self._elastic_key(node.args[0]):
                    desc = (f"lifecycle annotation removal "
                            f"{recv}.pop({node.args[0].id}, ...)")
            elif isinstance(node, (ast.Assign, ast.Delete)):
                targets = node.targets
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript) \
                            and self._elastic_key(tgt.slice):
                        recv = dotted_name(tgt.value) or "<expr>"
                        desc = (f"lifecycle annotation rewrite "
                                f"{recv}[...]")
                        break
            if desc is None:
                continue
            fn = mod.enclosing_function(node.lineno)
            if fn is not None and ctx.witness_in_scope(fn, self.WITNESS):
                continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"{desc} in {where} without a journaled control record "
                f"(_journal_elastic / record_control) on the path; "
                f"elastic grows, shrinks and lifecycle verbs ride the "
                f"journaled+fenced funnel only "
                f"(docs/design/elastic-gangs.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT016 — store verbs ride the retrying-transport funnel (store boundary)
# ---------------------------------------------------------------------------

class StoreVerbFunnelRule(Rule):
    """Scheduler-side store writes must flow through the retrying-
    transport funnel (store_transport.RetryingStoreTransport — bounded
    retry, backoff+jitter, per-cycle budget, resync degradation;
    docs/robustness.md store failure model). The funnel is a runtime
    composition, so statically the contract is scoping: the only code
    allowed to invoke store verbs directly is the executor funnel layer
    (cache/executors.py Store*), the transports themselves, and the
    federation CAS funnel (store_backed.py, whose fresh-read-and-reapply
    retry the generic transport cannot provide). A bare verb call
    anywhere else in scheduler scope is a write that crashes the cycle
    on the first transient apiserver error.

    Matched verbs: the distinctive store surface (``bind_pod``,
    ``evict_pod``, ``update_status``, ``create_batch``) on any receiver,
    plus the generic CRUD verbs (``create``/``update``/``delete``) when
    the receiver names a store (``self.store.update(...)``,
    ``store.create(...)`` — ``dict.update`` and friends stay out)."""

    id = "VT016"
    name = "store-verb-funnel"
    contract = ("scheduler-side store verb call outside the retrying-"
                "transport funnel (store failure model, "
                "docs/robustness.md)")
    scope = ("volcano_tpu/scheduler.py", "volcano_tpu/actions/",
             "volcano_tpu/framework/", "volcano_tpu/cache/",
             "volcano_tpu/plugins/", "volcano_tpu/federation/")
    # executors.py IS the funnel layer the transports compose under;
    # store_backed.py is the federation CAS funnel (per-transition
    # conflict retry with fresh reads)
    exclude = ("volcano_tpu/cache/executors.py",
               "volcano_tpu/federation/store_backed.py",
               "volcano_tpu/analysis/")

    DISTINCT_VERBS = {"bind_pod", "evict_pod", "update_status",
                      "create_batch"}
    GENERIC_VERBS = {"create", "update", "delete"}

    def _is_store_verb(self, node: ast.Call) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute):
            return None
        verb = node.func.attr
        recv = dotted_name(node.func.value)
        if verb in self.DISTINCT_VERBS:
            return f"{recv or '<expr>'}.{verb}"
        if verb in self.GENERIC_VERBS and recv is not None \
                and "store" in recv.split(".")[-1].lower():
            return f"{recv}.{verb}"
        return None

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._is_store_verb(node)
            if target is None:
                continue
            fn = mod.enclosing_function(node.lineno)
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"store verb {target}(...) in {where} outside the "
                f"retrying-transport funnel; scheduler-side store writes "
                f"ride store_transport.RetryingStoreTransport so a "
                f"transient apiserver error degrades to resync instead "
                f"of crashing the cycle (docs/robustness.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT017 — in-flight ledger + FeedbackChannel funnel (feedback failure model)
# ---------------------------------------------------------------------------

class InflightLedgerRule(Rule):
    """The feedback plane's two funnels (docs/robustness.md feedback
    failure model), statically pinned:

    1. Every executor-effecting bind/evict invocation must have a
       ``_register_inflight`` call on the path (same function or one
       hop) — an executor-accepted side effect with no armed ack
       deadline is exactly the state a lost kubelet ack wedges forever
       (the watchdog can only re-validate what the ledger knows about).

    2. Ack consumption — a ``cache.update_task_status(...)`` call in the
       ack-consuming scopes (the sim's cluster feedback, the store
       wiring's pod watch handlers) — must route through the
       FeedbackChannel normalizer (``ack_running`` / ``ack_evicted`` /
       ``pod_status_event`` on the path): a raw status flip would let a
       duplicate RUNNING ack resurrect a dead placement or a reordered
       evict/bind ack pair settle to the EARLIER intent.

    The executor layer, the journal's reconciler, the chaos wrappers,
    and the feedback/ledger modules themselves are exempt by design."""

    id = "VT017"
    name = "inflight-ledger"
    contract = ("executor-effecting bind/evict outside the in-flight "
                "ledger registration funnel, or ack consumption outside "
                "the FeedbackChannel normalizer (feedback failure "
                "model, docs/robustness.md)")
    exclude = ("volcano_tpu/cache/executors.py",
               "volcano_tpu/cache/journal.py", "volcano_tpu/chaos.py",
               "volcano_tpu/cache/feedback.py",
               "volcano_tpu/cache/inflight.py",
               "volcano_tpu/analysis/")

    EXECUTOR_ATTRS = {"binder", "evictor"}
    EXECUTOR_METHODS = {"bind", "evict"}
    LEDGER_WITNESS = {"_register_inflight"}
    ACK_SCOPE = ("volcano_tpu/sim/", "volcano_tpu/cache/store_wiring.py")
    ACK_WITNESS = {"ack_running", "ack_evicted", "pod_status_event"}

    def _is_executor_call(self, node: ast.Call) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in self.EXECUTOR_METHODS:
            return None
        recv = dotted_name(node.func.value)
        if recv is None:
            return None
        if recv.split(".")[-1] in self.EXECUTOR_ATTRS:
            return f"{recv}.{node.func.attr}"
        return None

    def _is_ack_consumption(self, node: ast.Call) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute) \
                or node.func.attr != "update_task_status":
            return None
        recv = dotted_name(node.func.value)
        # JobInfo carries an update_task_status too; only the CACHE-level
        # call is an ack consumption (the receiver heuristic VT016 uses)
        if recv is None or "cache" not in recv.split(".")[-1].lower():
            return None
        return f"{recv}.{node.func.attr}"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        in_ack_scope = _in_scope(mod.path, self.ACK_SCOPE)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._is_executor_call(node)
            if target is not None:
                fn = mod.enclosing_function(node.lineno)
                if fn is not None and ctx.witness_in_scope(
                        fn, self.LEDGER_WITNESS):
                    continue
                where = fn.qualname if fn else "<module>"
                findings.append(self.finding(
                    mod, node,
                    f"executor invocation {target}(...) in {where} "
                    f"without a _register_inflight record on the path; "
                    f"an executor-accepted side effect with no armed ack "
                    f"deadline wedges forever when its cluster ack is "
                    f"lost (docs/robustness.md feedback failure model)"))
                continue
            if not in_ack_scope:
                continue
            target = self._is_ack_consumption(node)
            if target is None:
                continue
            fn = mod.enclosing_function(node.lineno)
            if fn is not None and ctx.witness_in_scope(fn,
                                                      self.ACK_WITNESS):
                continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"ack consumption {target}(...) in {where} outside the "
                f"FeedbackChannel normalizer; kubelet/status acks enter "
                f"the cache through ack_running/ack_evicted/"
                f"pod_status_event so duplicates, reorders and stale "
                f"replays cannot resurrect dead placements "
                f"(docs/robustness.md feedback failure model)"))
        return findings


# ---------------------------------------------------------------------------
# VT005 — SimKill tunneling (PR 4, docs/robustness.md)
# ---------------------------------------------------------------------------

class SimKillSwallowRule(Rule):
    """``SimKill(BaseException)`` models SIGKILL: it must tunnel through
    every cycle-path handler. Handlers that catch BaseException (or are
    bare) must re-raise; catching SimKill by name is reserved for the
    sim's restart harness."""

    id = "VT005"
    name = "simkill-swallow"
    contract = ("except-BaseException/bare-except in cycle code without "
                "re-raise would swallow SimKill (PR 4 crash recovery)")
    scope = ("volcano_tpu/scheduler.py", "volcano_tpu/framework/",
             "volcano_tpu/actions/", "volcano_tpu/plugins/",
             "volcano_tpu/cache/", "volcano_tpu/sim/",
             "volcano_tpu/obs/")
    # the restart harness IS the process boundary: it may catch SimKill
    HARNESS_PATHS = ("volcano_tpu/sim/runner.py",)

    BROAD = {"BaseException"}
    KILL = {"SimKill"}

    def _handler_types(self, h: ast.ExceptHandler) -> List[Optional[str]]:
        if h.type is None:
            return [None]
        if isinstance(h.type, ast.Tuple):
            return [dotted_name(e) for e in h.type.elts]
        return [dotted_name(h.type)]

    def _reraises(self, h: ast.ExceptHandler) -> bool:
        for node in ast.walk(h):
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    return True
                if isinstance(node.exc, ast.Name) and h.name \
                        and node.exc.id == h.name:
                    return True
        return False

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                resolved = mod.resolve_call(node) or ""
                if resolved.endswith("contextlib.suppress") or \
                        resolved == "suppress":
                    for arg in node.args:
                        if (dotted_name(arg) or "").split(".")[-1] \
                                in self.BROAD | self.KILL:
                            findings.append(self.finding(
                                mod, node,
                                "contextlib.suppress over BaseException/"
                                "SimKill swallows simulated process death "
                                "(docs/robustness.md)"))
                continue
            if not isinstance(node, ast.ExceptHandler):
                continue
            types = self._handler_types(node)
            names = {(t or "").split(".")[-1] for t in types}
            broad = (None in types) or (names & self.BROAD)
            kills = names & self.KILL
            if kills and mod.path not in self.HARNESS_PATHS:
                findings.append(self.finding(
                    mod, node,
                    "except SimKill outside the sim restart harness: a "
                    "simulated SIGKILL must tunnel to the kill point "
                    "(docs/robustness.md)"))
                continue
            if broad and not self._reraises(node):
                what = "bare except:" if None in types \
                    else "except BaseException"
                findings.append(self.finding(
                    mod, node,
                    f"{what} without re-raise in cycle code would swallow "
                    f"SimKill/KeyboardInterrupt; re-raise BaseExceptions "
                    f"(docs/robustness.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT006 — pow2 shape bucketing (PR 3/4, docs/performance.md)
# ---------------------------------------------------------------------------

class ShapeBucketRule(Rule):
    """Every jitted-solver invocation must route its data-dependent array
    shapes through a pow2 bucketing/padding helper (``_bucket``,
    ``_job_bucket``, ``_delta_bucket``, ``bucket_chunks``, ...) somewhere
    on the REACHABLE PATH — the function itself, a transitive caller, or
    a transitive callee (this PR re-pointed the rule from one-hop to the
    transitive CallGraph closures; the id stays VT006 for baseline
    continuity). An unbucketed axis mints a fresh XLA program per
    distinct size, the multi-second churn recompile hole PR 4 closed.
    VT012 runs the SAME witness over the invocation sites only the
    dataflow lattice can see."""

    id = "VT006"
    name = "shape-bucket"
    contract = ("jit/shard_map entry points whose shape arguments skip "
                "pow2 bucketing re-open the churn recompile hole (PR 4; "
                "transitive-reach engine since PR 11)")
    scope = ("volcano_tpu/actions/", "volcano_tpu/ops/",
             "volcano_tpu/parallel/", "volcano_tpu/cache/snapshot.py")

    JIT_FACTORIES = {"jax.jit", "jit"}
    BUCKET_HINT = "bucket"
    BUCKET_EXTRA = {"padded_shape", "pow2"}

    def _is_jit_factory_call(self, mod: ModuleInfo,
                             node: ast.Call) -> bool:
        resolved = mod.resolve_call(node)
        return resolved in ("jax.jit",) or resolved == "jit"

    def _jit_producers(self, ctx: AnalysisContext) -> Set[str]:
        """Function names (package-wide) that return/cache a jax.jit
        result — calling their return value launches a compiled
        program. Cached on the context: this is a full-package AST walk
        and both VT006 and VT012 consult it per module."""
        cached = getattr(ctx, "_jit_producers", None)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for m in ctx.modules:
            for fn in m.functions:
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Call) \
                            and self._is_jit_factory_call(m, node):
                        out.add(fn.name)
        ctx._jit_producers = out               # type: ignore[attr-defined]
        return out

    def _has_bucket(self, fn: FunctionInfo) -> bool:
        for name in fn.called_names:
            if self.BUCKET_HINT in name or name in self.BUCKET_EXTRA:
                return True
        return False

    def _module_jit_attrs(self, mod: ModuleInfo,
                          producers: Set[str]) -> Set[str]:
        """Attributes assigned from a jit factory/producer ANYWHERE in
        the module (``self._solve = _job_solver()`` in __init__, invoked
        from another method)."""
        out: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                src = node.value
                is_jit = self._is_jit_factory_call(mod, src) or (
                    isinstance(src.func, ast.Name)
                    and src.func.id in producers)
                if not is_jit:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        out.add(tgt.attr)
        return out

    def bucket_on_path(self, fn: FunctionInfo,
                       ctx: AnalysisContext) -> bool:
        """Transitive witness: a bucket/pad helper call in the function
        or anywhere on the reachable path (callers* ∪ callees*)."""
        if self._has_bucket(fn):
            return True
        return any(self._has_bucket(o) for o in ctx.graph.reach(fn))

    def syntactic_sites(self, mod: ModuleInfo, ctx: AnalysisContext
                        ) -> Dict[int, List[Tuple[ast.Call, str]]]:
        """id(fn) -> jit invocation sites found by the NAME heuristics
        (producer-bound names/attrs, solver-valued parameters). VT012
        subtracts these lines so the two rules never double-report;
        per-module results are cached on the context so the two rules
        share one computation."""
        cache = getattr(ctx, "_vt006_sites", None)
        if cache is None:
            cache = {}
            ctx._vt006_sites = cache           # type: ignore[attr-defined]
        hit = cache.get(mod.path)
        if hit is not None:
            return hit
        producers = self._jit_producers(ctx)
        module_jit_attrs = self._module_jit_attrs(mod, producers)
        out: Dict[int, List[Tuple[ast.Call, str]]] = {}
        for fn in mod.functions:
            sites = self._fn_sites(fn, producers, module_jit_attrs)
            if sites:
                out[id(fn)] = sites
        cache[mod.path] = out
        return out

    def _fn_sites(self, fn: FunctionInfo, producers: Set[str],
                  module_jit_attrs: Set[str]
                  ) -> List[Tuple[ast.Call, str]]:
        mod = fn.module
        # names/attrs bound from a jit factory or producer inside fn,
        # plus solver-valued parameters (the batched engines thread
        # the compiled callable through helpers by argument)
        jit_vars: Set[str] = set(module_jit_attrs)
        for arg in ast.walk(getattr(fn.node, "args", ast.arguments(
                posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
                defaults=[]))):
            if isinstance(arg, ast.arg) and "solver" in arg.arg:
                jit_vars.add(arg.arg)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                src = node.value
                is_jit = self._is_jit_factory_call(mod, src) or (
                    isinstance(src.func, ast.Name)
                    and src.func.id in producers)
                if not is_jit:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        jit_vars.add(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        jit_vars.add(tgt.attr)
        invocations: List[Tuple[ast.Call, str]] = []
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            # _job_solver()(...)  — calling a producer's return value
            if isinstance(node.func, ast.Call) \
                    and isinstance(node.func.func, ast.Name) \
                    and node.func.func.id in producers:
                invocations.append((node, node.func.func.id + "()"))
            # solver(...) where solver was bound from a producer/jit
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in jit_vars:
                invocations.append((node, node.func.id))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in jit_vars:
                invocations.append((node, node.func.attr))
        return invocations

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn_id, invocations in self.syntactic_sites(mod, ctx).items():
            fn = next(f for f in mod.functions if id(f) == fn_id)
            if self.bucket_on_path(fn, ctx):
                continue
            node, desc = invocations[0]
            findings.append(self.finding(
                mod, node,
                f"jitted solver invocation {desc}(...) in {fn.qualname} "
                f"with no pow2 bucket/pad helper anywhere on the "
                f"reachable path (transitive callers/callees); unbucketed "
                f"shapes mint a fresh XLA compile per size "
                f"(docs/performance.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT007 — lock discipline in shared-state modules (PR 5)
# ---------------------------------------------------------------------------

class LockDisciplineRule(Rule):
    """native/, metrics/ and obs/trace.py are read and written from the
    scheduler loop, watch/controller threads and the metrics HTTP server
    at once: every write to shared state (self.* of a lock-owning class,
    module globals of a lock-owning module) must happen under the lock,
    in a ``*_locked`` helper, or in a function only ever called with the
    lock held (one hop)."""

    id = "VT007"
    name = "lock-discipline"
    contract = ("shared-state write outside a held lock in native/, "
                "metrics/, obs/trace.py (PR 5 observability)")
    scope = ("volcano_tpu/native/", "volcano_tpu/metrics/",
             "volcano_tpu/obs/trace.py")

    MUTATING_METHODS = {"append", "appendleft", "add", "pop", "popleft",
                        "clear", "update", "setdefault", "remove",
                        "extend", "discard", "insert"}
    EXEMPT_FUNCS = {"__init__", "__new__", "__del__", "__enter__",
                    "__exit__"}

    @staticmethod
    def _lock_names(mod: ModuleInfo) -> Tuple[Set[str], Set[str]]:
        """(class-attr lock names, module-global lock names): anything
        bound from threading.Lock/RLock or named *lock*."""
        attr_locks: Set[str] = set()
        global_locks: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            is_lock_val = isinstance(node.value, ast.Call) and \
                (mod.resolve_call(node.value) or "").split(".")[-1] \
                in ("Lock", "RLock")
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and (
                        is_lock_val or "lock" in tgt.attr.lower()):
                    # the name heuristic catches locks the value-shape
                    # check cannot see (aliased factories, locks passed
                    # in through a parameter) — without it their `with
                    # self._x_lock:` guards are invisible and guarded
                    # writes false-positive
                    attr_locks.add(tgt.attr)
                elif isinstance(tgt, ast.Name) and is_lock_val:
                    global_locks.add(tgt.id)
        return attr_locks, global_locks

    def _under_lock(self, fn: FunctionInfo, node: ast.AST,
                    locks: Set[str]) -> bool:
        """Is ``node`` lexically inside a ``with <lock>:`` in ``fn``?"""
        for w in ast.walk(fn.node):
            if not isinstance(w, ast.With):
                continue
            held = False
            for item in w.items:
                d = dotted_name(item.context_expr) or ""
                if d.split(".")[-1] in locks:
                    held = True
            if not held:
                continue
            if w.lineno <= node.lineno <= getattr(w, "end_lineno",
                                                  w.lineno):
                return True
        return False

    def _callers_hold_lock(self, fn: FunctionInfo, ctx: AnalysisContext,
                           locks: Set[str]) -> bool:
        callers = ctx.graph.callers_of(fn)
        if not callers:
            return False
        for caller in callers:
            held = False
            for node in ast.walk(caller.node):
                if isinstance(node, ast.Call) and (
                        (isinstance(node.func, ast.Name)
                         and node.func.id == fn.name)
                        or (isinstance(node.func, ast.Attribute)
                            and node.func.attr == fn.name)):
                    if self._under_lock(caller, node, locks):
                        held = True
                    else:
                        return False
            if not held:
                return False
        return True

    def _module_global_names(self, mod: ModuleInfo) -> Set[str]:
        out: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                out.add(node.target.id)
        return out

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        attr_locks, global_locks = self._lock_names(mod)
        locks = attr_locks | global_locks
        if not locks:
            return []
        module_globals = self._module_global_names(mod)
        # classes that own a lock (assign a lock attr in their methods)
        lock_classes: Set[str] = set()
        for fn in mod.functions:
            if fn.cls is None:
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and tgt.attr in attr_locks \
                                and dotted_name(tgt.value) == "self":
                            lock_classes.add(fn.cls)
        findings: List[Finding] = []
        for fn in mod.functions:
            if fn.name in self.EXEMPT_FUNCS or fn.name.endswith("_locked"):
                continue
            writes: List[Tuple[ast.AST, str]] = []
            for node in ast.walk(fn.node):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in targets:
                        base = tgt
                        while isinstance(base, ast.Subscript):
                            base = base.value
                        if isinstance(base, ast.Attribute) \
                                and dotted_name(base.value) == "self" \
                                and fn.cls in lock_classes \
                                and base.attr not in attr_locks:
                            writes.append((node, f"self.{base.attr}"))
                        elif isinstance(base, ast.Name) \
                                and base.id in module_globals \
                                and global_locks \
                                and self._declared_global(fn, base.id):
                            writes.append((node, base.id))
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in self.MUTATING_METHODS:
                    recv = node.func.value
                    while isinstance(recv, ast.Subscript):
                        recv = recv.value
                    d = dotted_name(recv) or ""
                    parts = d.split(".")
                    if parts[0] == "self" and len(parts) == 2 \
                            and fn.cls in lock_classes:
                        writes.append((node, d))
                    elif len(parts) == 1 and parts[0] in module_globals \
                            and global_locks:
                        writes.append((node, d))
            unguarded = [(n, d) for n, d in writes
                         if not self._under_lock(fn, n, locks)]
            if not unguarded:
                continue
            if self._callers_hold_lock(fn, ctx, locks):
                continue
            node, desc = unguarded[0]
            findings.append(self.finding(
                mod, node,
                f"write to shared state {desc} in {fn.qualname} outside a "
                f"held lock; guard it, rename the helper *_locked, or "
                f"call it only under the lock (docs/observability.md)"))
        return findings

    @staticmethod
    def _declared_global(fn: FunctionInfo, name: str) -> bool:
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Global) and name in node.names:
                return True
        return False


# ---------------------------------------------------------------------------
# VT010–VT014 — the dataflow rules (PR 11, analysis/dataflow.py)
# ---------------------------------------------------------------------------

class HostSyncRule(Rule):
    """Implicit host↔device synchronization on a device-tainted value —
    ``np.*``, ``float``/``int``/``bool``/``len``, ``.item()``, iteration,
    a branch test, ``jax.device_get``/``block_until_ready`` — outside an
    allowlisted replay/readback span. Every such site serializes the
    device stream against the host and is therefore a blocker for
    overlapping cycle N+1's solve with cycle N's commit (ROADMAP item 2):
    the findings ARE the async-overlap worklist, each reporting the sync
    operation AND the producing expression.

    Excusals (both MAY-biased, see dataflow.py's design note):
    - the site runs under one of the sanctioned readback/commit spans
      (lexically, or inherited through CallGraph.span_context) — those
      phases exist to fetch;
    - a structured READBACK_ALLOWLIST entry matches (path, symbol): the
      deliberate one-fetch sites, each carrying its reason."""

    id = "VT010"
    name = "host-sync"
    contract = ("implicit host sync on a device-tainted value outside an "
                "allowlisted replay/readback span (PR 11 dataflow; the "
                "async-overlap worklist of ROADMAP item 2)")
    scope = ("volcano_tpu/actions/", "volcano_tpu/ops/",
             "volcano_tpu/parallel/", "volcano_tpu/cache/",
             "volcano_tpu/framework/")

    # the sanctioned fetch/commit phases of the cycle trace (PR 5 spans):
    # a sync under one of these is the scheduled readback, not a leak
    ALLOWED_SPANS = {"solve", "replay", "upload", "bind_commit"}

    # deliberate one-fetch / blocking sites outside any span, each with
    # its reason — the structured allowlist the tentpole issue specifies.
    # Match is on (path, enclosing symbol, sync kind) — the kind keeps an
    # entry from silently covering a DIFFERENT sync that later appears in
    # the same function. Keep entries FEW and justified.
    # Burn-down history (ROADMAP item 2): the _DeviceJobPlacer.place
    # entry was retired by PR 12 — its per-job fetch now runs under the
    # sanctioned ``solve`` span, and the pipelined dispatch/await split
    # (dispatch_speculative_solve / finalize_speculative_dispatch) means
    # replay readbacks await the PREVIOUS cycle's transfer instead of
    # blocking their own. Only the startup-prewarm block remains.
    READBACK_ALLOWLIST = (
        {"path": "volcano_tpu/actions/allocate.py",
         "symbol": "prewarm_shapes",
         "kind": "jax.block_until_ready",
         "reason": "startup prewarm must block until every warmed shape "
                   "finishes compiling; it runs from Scheduler.prewarm, "
                   "never inside a scheduling cycle"},
    )

    def classify(self, mod: ModuleInfo, fn: FunctionInfo, site,
                 ctx: AnalysisContext) -> Tuple[str, str]:
        """The ONE excusal ladder, shared by check() and the CLI's
        --sync-inventory so the printed worklist can never drift from
        what CI gates. Returns (status, detail):
        ("span", names) | ("allowlist", reason) |
        ("out-of-scope", "") | ("blocking", "")."""
        line = getattr(site.node, "lineno", fn.node.lineno)
        spans = enclosing_span_names(fn, line) | ctx.graph.span_context(fn)
        excused = sorted(spans & self.ALLOWED_SPANS)
        if excused:
            return ("span", ",".join(excused))
        for e in self.READBACK_ALLOWLIST:
            if (e["path"], e["symbol"], e["kind"]) == \
                    (mod.path, fn.qualname, site.kind):
                return ("allowlist", e["reason"])
        if not self.applies_to(mod.path):
            return ("out-of-scope", "")
        return ("blocking", "")

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        from .dataflow import get_dataflow
        df = get_dataflow(ctx)
        findings: List[Finding] = []
        for fn in mod.functions:
            for site in df.facts(fn).sync_sites:
                status, _ = self.classify(mod, fn, site, ctx)
                if status != "blocking":
                    continue
                findings.append(self.finding(
                    mod, site.node,
                    f"implicit host sync ({site.kind}) on a device value "
                    f"produced by {site.producer} in {fn.qualname}, "
                    f"outside an allowlisted replay/readback span "
                    f"{sorted(self.ALLOWED_SPANS)}; this blocks "
                    f"solve/commit overlap — move it into the fetch "
                    f"phase, keep the value on device, or add a "
                    f"justified READBACK_ALLOWLIST entry "
                    f"(docs/static-analysis.md)"))
        return findings


class TracedBranchRule(Rule):
    """Python ``if``/``while``/``assert`` on a traced value inside a
    jit-entry function: under ``jax.jit`` the test either concretizes the
    tracer (TracerBoolConversionError at best) or silently burns the
    branch into the compiled program and retraces per value. Control flow
    on traced data belongs in ``lax.cond``/``lax.while_loop``/
    ``jnp.where``. ``is None``/``isinstance`` tests are static and
    exempt, as are ``static_argnames`` parameters."""

    id = "VT011"
    name = "traced-branch"
    contract = ("Python if/while/assert on a traced value inside a "
                "jit-entry function — silent retrace/concretization "
                "hazard (PR 11 dataflow)")
    scope = ("volcano_tpu/actions/", "volcano_tpu/ops/",
             "volcano_tpu/parallel/", "volcano_tpu/cache/")

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        from .dataflow import get_dataflow
        df = get_dataflow(ctx)
        findings: List[Finding] = []
        for fn in mod.functions:
            for node, producer in df.facts(fn).traced_tests:
                findings.append(self.finding(
                    mod, node,
                    f"Python branch on a traced value ({producer}) inside "
                    f"jit-entry {fn.qualname}; use lax.cond/lax.while_loop/"
                    f"jnp.where — a concrete branch silently retraces per "
                    f"value (docs/static-analysis.md)"))
        return findings


class DataflowShapeBucketRule(Rule):
    """The dataflow half of the shape-bucketing contract: jit invocation
    sites only the taint lattice can see — a compiled callable threaded
    through an arbitrarily-named parameter, a cache dict, a return
    value — still need a pow2 bucket/pad helper on the reachable path.
    Sites VT006's name heuristics already report are skipped, so the two
    rules partition the invocation set (VT006 keeps its id for baseline
    continuity; both use the same transitive witness)."""

    id = "VT012"
    name = "shape-bucket-dataflow"
    contract = ("dataflow-detected jit invocation with no pow2 bucket/pad "
                "helper on the reachable path (PR 11; supersedes VT006's "
                "one-hop heuristic)")
    scope = ShapeBucketRule.scope

    def __init__(self) -> None:
        self._syntactic = ShapeBucketRule()

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        from .dataflow import get_dataflow
        df = get_dataflow(ctx)
        syntactic = self._syntactic.syntactic_sites(mod, ctx)
        findings: List[Finding] = []
        for fn in mod.functions:
            known = {n.lineno for n, _ in syntactic.get(id(fn), [])}
            for jc in df.facts(fn).jit_calls:
                if jc.node.lineno in known:
                    continue            # VT006's site; one rule reports
                if self._syntactic.bucket_on_path(fn, ctx):
                    continue
                findings.append(self.finding(
                    mod, jc.node,
                    f"jitted callable {jc.desc}(...) invoked in "
                    f"{fn.qualname} (dataflow-traced) with no pow2 "
                    f"bucket/pad helper anywhere on the reachable path; "
                    f"unbucketed shapes mint a fresh XLA compile per "
                    f"size (docs/performance.md)"))
        return findings


class DtypeDisciplineRule(Rule):
    """Weak-dtype operands feeding jitted solvers: a bare Python numeric
    literal passed positionally, or an ``np.arange``/``np.zeros``-family
    array built WITHOUT an explicit dtype, reaching a jit invocation.
    Weak-typed operands re-key the compile cache when promotion changes
    (a recompile per literal pattern) and silently truncate under the
    x64-disabled default (int64→int32, float64→float32) — the solver
    sees different numbers than the host accounting. Keyword literals
    are exempt: they are the ``static_argnames`` convention."""

    id = "VT013"
    name = "dtype-discipline"
    contract = ("bare literal / dtype-less np.arange/np.zeros-family "
                "operand flowing into a jitted solver — weak-type "
                "recompile and x64-truncation hazard (PR 11 dataflow)")
    scope = ShapeBucketRule.scope

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        from .dataflow import get_dataflow
        df = get_dataflow(ctx)
        findings: List[Finding] = []
        for fn in mod.functions:
            for jc in df.facts(fn).jit_calls:
                for arg_node, desc, producer in jc.weak_args:
                    findings.append(self.finding(
                        mod, arg_node,
                        f"weak-dtype operand {desc} ({producer}) feeds "
                        f"jitted call {jc.desc}(...) in {fn.qualname}; "
                        f"pass an explicit dtype so the compile key and "
                        f"the x64-disabled value range are pinned "
                        f"(docs/static-analysis.md)"))
        return findings


class SessionEscapeRule(Rule):
    """Session-lifetime escape: a session-scoped value (derived from an
    open Session/snapshot) stored where it outlives ``close_session``/
    ``abandon_session`` — a module global, a module-global container, or
    an attribute of a long-lived class. Exactly the bug class PR 3's
    ``_touched`` mutation witness catches dynamically (session pipeline
    state leaking through reused snapshot clones), now caught statically.
    Self-stores are only checked in the long-lived infrastructure
    modules (scheduler/cache/controllers/...): per-cycle helper objects
    in actions/ die with the session by construction, and classes whose
    ``__init__`` takes the session (or that are per-session-rebuilt
    plugins) are session-scoped themselves."""

    id = "VT014"
    name = "session-escape"
    contract = ("session-scoped value stored on a module global or a "
                "long-lived object — outlives close_session/"
                "abandon_session (PR 11 dataflow; the PR 3 witness bug "
                "class, statically)")
    scope = ("volcano_tpu/scheduler.py", "volcano_tpu/actions/",
             "volcano_tpu/cache/", "volcano_tpu/framework/",
             "volcano_tpu/plugins/", "volcano_tpu/sim/",
             "volcano_tpu/federation/", "volcano_tpu/controllers/")

    # modules whose classes outlive scheduling sessions: a session-tainted
    # self-store here escapes the session lifetime
    LONG_LIVED = ("volcano_tpu/scheduler.py", "volcano_tpu/cache/",
                  "volcano_tpu/controllers/", "volcano_tpu/federation/",
                  "volcano_tpu/sim/")

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        from .dataflow import get_dataflow
        df = get_dataflow(ctx)
        long_lived = _in_scope(mod.path, self.LONG_LIVED)
        findings: List[Finding] = []
        for fn in mod.functions:
            for node, target, producer in df.facts(fn).session_escapes:
                if target.startswith("self.") and not long_lived:
                    continue
                findings.append(self.finding(
                    mod, node,
                    f"session-scoped value ({producer}) stored in "
                    f"{target} by {fn.qualname}; it outlives "
                    f"close_session/abandon_session — derive it per "
                    f"cycle, or justify why the holder may keep it "
                    f"(docs/static-analysis.md)"))
        return findings


class SpeculationIsolationRule(Rule):
    """Speculation isolation (PR 12, docs/performance.md pipelining): the
    speculative-open path — staging the snapshot, opening the speculative
    session, dispatching the solve — must be READ-ONLY with respect to
    the scheduler's durable and decision state. Any side-effect write
    reachable from a speculative root that lands on the SchedulerCache
    funnels, the intent journal, or an executor OUTSIDE the commit funnel
    is a finding: a crash between dispatch and commit must lose only
    speculative state (nothing journaled, zero double-binds — the
    pipelined chaos soak's contract).

    Mechanics: BFS over the call graph from ``SPECULATIVE_ROOTS``,
    following only UNAMBIGUOUS simple-name edges (exactly one def in the
    package — the same precision rule as CallGraph.span_context, biased
    against smearing), never entering the ``COMMIT_GATE`` functions (the
    sanctioned commit boundary, which runs after the conflict check on
    the cycle's real session). Every function in the closure is scanned
    for sink calls (``<cache|binder|evictor|journal|status_updater|ssn>
    .<bind|bind_batch|evict|allocate|pipeline|dispatch|record_intent|
    _journal_intent|ack|resync_task|redrive_dead_letter>``) and for
    assignments into the cache's object indexes."""

    id = "VT015"
    name = "speculation-isolation"
    contract = ("write reachable from the speculative-open path landing "
                "on SchedulerCache/journal/executors outside the commit "
                "funnel (PR 12; docs/performance.md pipelining)")
    scope = ("volcano_tpu/scheduler.py", "volcano_tpu/actions/",
             "volcano_tpu/framework/", "volcano_tpu/cache/")

    SPECULATIVE_ROOTS = ("_dispatch_speculation",
                         "dispatch_speculative_solve",
                         "speculative_snapshot",
                         "tensor_refresh_speculative")
    COMMIT_GATE = ("_commit_speculation", "_check_speculation",
                   "finalize_speculative_dispatch")
    SINK_ATTRS = {"bind", "bind_batch", "evict", "allocate", "pipeline",
                  "dispatch", "record_intent", "_journal_intent", "ack",
                  "resync_task", "redrive_dead_letter"}
    SINK_RECEIVERS = {"cache", "binder", "evictor", "journal",
                      "status_updater", "ssn", "session", "sssn"}
    INDEX_ATTRS = {"jobs", "nodes", "queues", "dead_letter",
                   "binding_tasks"}

    def _closure(self, ctx: AnalysisContext) -> List[FunctionInfo]:
        graph = ctx.graph
        frontier = [fn for name in self.SPECULATIVE_ROOTS
                    for fn in graph.defs.get(name, [])]
        seen = {id(fn): fn for fn in frontier}
        while frontier:
            nxt: List[FunctionInfo] = []
            for fn in frontier:
                for name in fn.linkable_calls:
                    targets = graph.defs.get(name)
                    if not targets or len(targets) > 1:
                        continue        # ambiguous: do not smear
                    (callee,) = targets
                    if callee.name in self.COMMIT_GATE:
                        continue        # the sanctioned commit boundary
                    if id(callee) not in seen:
                        seen[id(callee)] = callee
                        nxt.append(callee)
            frontier = nxt
        return list(seen.values())

    def _sinks_in(self, fn: FunctionInfo):
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                dotted = dotted_name(node.func)
                if dotted is None:
                    continue
                parts = dotted.split(".")
                if len(parts) >= 2 and parts[-1] in self.SINK_ATTRS \
                        and set(parts[:-1]) & self.SINK_RECEIVERS:
                    yield node, f"call {dotted}(...)"
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    base = tgt
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    dotted = dotted_name(base)
                    if dotted is None:
                        continue
                    parts = dotted.split(".")
                    if parts[-1] in self.INDEX_ATTRS \
                            and set(parts[:-1]) & self.SINK_RECEIVERS:
                        yield node, f"write to {dotted}"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        closure = getattr(ctx, "_vt015_closure", None)
        if closure is None:
            closure = self._closure(ctx)
            ctx._vt015_closure = closure
        findings: List[Finding] = []
        for fn in closure:
            if fn.module is not mod:
                continue
            for node, desc in self._sinks_in(fn):
                findings.append(self.finding(
                    mod, node,
                    f"{desc} in {fn.qualname}, reachable from the "
                    f"speculative-open path "
                    f"({'/'.join(self.SPECULATIVE_ROOTS[:2])}...): "
                    f"speculation must journal/execute NOTHING before "
                    f"the commit funnel — route the write through the "
                    f"commit boundary or off the speculative path "
                    f"(docs/static-analysis.md)"))
        return findings


# ---------------------------------------------------------------------------
# VT018 — bounded per-cycle work (overload failure model)
# ---------------------------------------------------------------------------

class BoundedWorkRule(Rule):
    """The cycle-budget companion contract (docs/robustness.md overload
    failure model): a loop over a PENDING/BACKLOG collection in
    scheduler-cycle scope is work that grows with the backlog — under
    sustained overload an unguarded walk stretches the cycle, which
    grows the backlog, which stretches the cycle. Every such loop must
    consult a budget/limit witness within reach:

    - a :class:`CycleBudget` check (``remaining``/``exhausted``/
      ``charge``) in the function or one call-graph hop;
    - a bounded slice of the iterable (``backlog[:max_items]``);
    - a max-items guard (``if n >= max_gangs: break``) — any
      break/return/continue gated on a ``budget``/``max``/``limit``/
      ``cap`` name;
    - a bound-named argument to the producing call
      (``pop_ready(max_items)`` — the callee owns the cap).

    Matched collections: dotted receivers naming
    pending/backlog/dead_letter/resync/new_job/retry state, the
    producer calls (``pop_ready``, ``drain_new_jobs``), and locals
    TAINTED by assignment from either (including through
    ``list``/``sorted`` wrappers and ``getattr(cache,
    "drain_new_jobs")`` indirection). Bare locals that merely happen to
    be named ``pending`` are not flagged — only provenance counts."""

    id = "VT018"
    name = "bounded-work"
    contract = ("loop over a pending/backlog collection in scheduler-"
                "cycle scope without a budget/limit witness "
                "(CycleBudget, slice, or max-items guard) within "
                "reach (docs/robustness.md overload failure model)")
    scope = ("volcano_tpu/scheduler.py", "volcano_tpu/cache/cache.py",
             "volcano_tpu/federation/rebalance.py")

    import re as _re
    COLLECTION_RE = _re.compile(
        r"(pending|backlog|dead_letter|resync|new_job|retry_heap)")
    PRODUCER_CALLS = {"pop_ready", "drain_new_jobs"}
    BUDGET_WITNESS = {"remaining", "exhausted", "charge"}
    BOUND_NAME_RE = _re.compile(r"(budget|max|limit|cap)", _re.I)

    # -- collection matching -------------------------------------------------

    def _attr_matches(self, node: ast.AST) -> bool:
        """Dotted receivers only: ``self.dead_letter.items()`` matches,
        a bare local coincidentally named ``pending`` does not."""
        dn = dotted_name(node)
        return bool(dn and "." in dn and self.COLLECTION_RE.search(dn))

    def _call_matches(self, node: ast.Call, tainted: Set[str]) -> bool:
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else "")
        if fname in self.PRODUCER_CALLS:
            return True
        if isinstance(f, ast.Name):
            if f.id in tainted:
                return True
            if f.id in ("list", "sorted", "tuple", "set"):
                return any(self._expr_matches(a, tainted)
                           for a in node.args)
            if f.id == "getattr" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and str(node.args[1].value) in self.PRODUCER_CALLS:
                return True
        if isinstance(f, ast.Attribute) and self._attr_matches(f.value):
            return True                     # self.dead_letter.items()
        return False

    def _expr_matches(self, node: ast.AST, tainted: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            return self._attr_matches(node)
        if isinstance(node, ast.Call):
            return self._call_matches(node, tainted)
        if isinstance(node, ast.Subscript):
            return self._expr_matches(node.value, tainted)
        return False

    def _taints(self, fn: FunctionInfo) -> Set[str]:
        """Locals assigned (transitively, to a fixpoint) from matching
        collections/producers."""
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not self._expr_matches(node.value, tainted):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id not in tainted:
                        tainted.add(tgt.id)
                        changed = True
        return tainted

    # -- witnesses -----------------------------------------------------------

    def _mentions_bound_name(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) \
                    and self.BOUND_NAME_RE.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) \
                    and self.BOUND_NAME_RE.search(sub.attr):
                return True
            if isinstance(sub, ast.keyword) and sub.arg \
                    and self.BOUND_NAME_RE.search(sub.arg):
                return True
        return False

    def _iter_witnessed(self, it: ast.AST) -> bool:
        """Witness ON the iterable itself: a bounded slice, or a
        bound-named argument to the producing call (the callee owns
        the cap — ``pop_ready(max_items)``)."""
        if isinstance(it, ast.Subscript) \
                and isinstance(it.slice, ast.Slice) \
                and it.slice.upper is not None:
            return True
        if isinstance(it, ast.Call) \
                and (any(self._mentions_bound_name(a) for a in it.args)
                     or any(self._mentions_bound_name(k)
                            for k in it.keywords)):
            return True
        return False

    def _guarded_exit(self, fn: FunctionInfo) -> bool:
        """A break/return/continue gated on a budget/max/limit/cap name
        anywhere in the function — the max-items guard form."""
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.If):
                continue
            if not self._mentions_bound_name(node.test):
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, (ast.Break, ast.Return,
                                        ast.Continue)):
                        return True
        return False

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in mod.functions:
            tainted = self._taints(fn)
            loops = [node for node in ast.walk(fn.node)
                     if isinstance(node, ast.For)
                     and self._expr_matches(node.iter, tainted)]
            if not loops:
                continue
            if ctx.witness_in_scope(fn, self.BUDGET_WITNESS):
                continue
            if self._guarded_exit(fn):
                continue
            for node in loops:
                if self._iter_witnessed(node.iter):
                    continue
                desc = dotted_name(node.iter) \
                    or (ast.unparse(node.iter)
                        if hasattr(ast, "unparse") else "<expr>")
                findings.append(self.finding(
                    mod, node,
                    f"loop over pending/backlog collection ({desc}) in "
                    f"{fn.qualname} without a budget/limit witness "
                    f"(CycleBudget check, bounded slice, or max-items "
                    f"guard) within reach; unbounded per-cycle work is "
                    f"the overload collapse spiral "
                    f"(docs/robustness.md overload failure model)"))
        return findings


# ---------------------------------------------------------------------------
# VT021 — mesh mutations carry a tensor-epoch bump
# ---------------------------------------------------------------------------

class MeshMutationWitnessRule(Rule):
    """Any call that changes the solver's device set — quarantining a
    faulted device out of the mesh, or readmitting a probed one — makes
    every persistent device tensor stale: the node layout was padded for
    the old D, and the uploaded shards live on a mesh that no longer
    exists. The mutation must therefore have a tensor-epoch bump
    (``invalidate_device_state`` / ``retire_epoch``) on the path, same
    function or one hop. A bare mutation is a heal that re-dispatches
    onto tensors shaped for the dead mesh — at best an XLA shape error,
    at worst a silently wrong placement read from a stale shard
    (docs/robustness.md mesh failure model)."""

    id = "VT021"
    name = "mesh-mutation-witness"
    contract = ("device-set mutation (quarantine/readmit) without a "
                "tensor-epoch bump (invalidate_device_state/retire_epoch) "
                "on the path (mesh fault containment, docs/robustness.md)")
    # device_health.py holds the raw lattice verbs themselves plus the
    # record_fault -> quarantine attribution delegation; it owns lattice
    # state only — the caller owns the epoch
    exclude = ("volcano_tpu/analysis/", "volcano_tpu/device_health.py")

    MUTATOR_METHODS = {"quarantine", "readmit"}
    WITNESS = {"invalidate_device_state", "retire_epoch"}

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in self.MUTATOR_METHODS:
                continue
            recv = dotted_name(node.func.value) or "<expr>"
            fn = mod.enclosing_function(node.lineno)
            if fn is not None:
                # a lattice verb's own def (store-backed or test-double
                # overrides) is the mutation floor, not a mesh decision
                if fn.name in self.MUTATOR_METHODS:
                    continue
                if ctx.witness_in_scope(fn, self.WITNESS):
                    continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"device-set mutation {recv}.{node.func.attr}(...) in "
                f"{where} without a tensor-epoch bump "
                f"(invalidate_device_state / retire_epoch) on the path; "
                f"persistent device tensors are shaped for the old mesh "
                f"and must be retired before the next dispatch "
                f"(docs/robustness.md mesh failure model)"))
        return findings


# ---------------------------------------------------------------------------
# VT022 — durable funnel records carry a lifecycle-timeline witness
# ---------------------------------------------------------------------------

class LifecycleEventWitnessRule(Rule):
    """Every durable record a decision funnel writes (a journal intent,
    a reserve/move/elastic control record) is a milestone in some job's
    cluster-causal story — and the per-job timeline (obs/lifecycle.py)
    is reconstructed FROM those records after a failover or queue move.
    A funnel that writes the record without stamping/forwarding a
    correlation ctx (``TIMELINE.stamp``/``record``/``ingest``, same
    function or one hop) produces a durable event no successor process
    can place on the timeline: the job's story silently breaks at
    exactly the handoff the observability layer exists to survive."""

    id = "VT022"
    name = "lifecycle-event-witness"
    contract = ("durable funnel record (record_intent/record_control) "
                "without a lifecycle-timeline witness (TIMELINE.stamp/"
                "record/ingest) on the path (cluster-causal "
                "observability, docs/observability.md)")
    # the decision funnels whose records carry per-job milestones; the
    # command funnel (elastic_gang/commands.py) journals operator-verb
    # ledger records, not job lifecycle events, and journal.py itself
    # defines the writers (it ingests, it does not originate)
    scope = ("volcano_tpu/cache/cache.py",
             "volcano_tpu/cache/feedback.py",
             "volcano_tpu/federation/reserve.py",
             "volcano_tpu/elastic_gang/grow_shrink.py")

    MUTATOR_METHODS = {"record_intent", "record_control"}
    WITNESS = {"stamp", "record", "ingest"}

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in self.MUTATOR_METHODS:
                continue
            recv = dotted_name(node.func.value) or "<expr>"
            fn = mod.enclosing_function(node.lineno)
            if fn is not None:
                # the writer's own def (an override/test double) is the
                # persistence floor, not a funnel decision
                if fn.name in self.MUTATOR_METHODS:
                    continue
                if ctx.witness_in_scope(fn, self.WITNESS):
                    continue
            where = fn.qualname if fn else "<module>"
            findings.append(self.finding(
                mod, node,
                f"durable funnel record {recv}.{node.func.attr}(...) in "
                f"{where} without a lifecycle-timeline witness "
                f"(TIMELINE.stamp / record / ingest) on the path; the "
                f"record cannot be placed on any job timeline after a "
                f"failover or queue move (docs/observability.md "
                f"cluster-causal model)"))
        return findings


ALL_RULES: List[Rule] = [
    DirtyWitnessRule(), RawClockRule(), UnseededRandomRule(),
    JournalFunnelRule(), SimKillSwallowRule(), ShapeBucketRule(),
    LockDisciplineRule(), FencingEpochRule(), CrossPartitionFunnelRule(),
    HostSyncRule(), TracedBranchRule(), DataflowShapeBucketRule(),
    DtypeDisciplineRule(), SessionEscapeRule(),
    SpeculationIsolationRule(), StoreVerbFunnelRule(),
    InflightLedgerRule(), BoundedWorkRule(), MembershipFunnelRule(),
    ElasticFunnelRule(), MeshMutationWitnessRule(),
    LifecycleEventWitnessRule(),
]

# the rules that run on the shared dataflow/callgraph engine
# (vlint --dataflow): VT015 rides the same interprocedural closure
DATAFLOW_RULE_IDS = ("VT006", "VT010", "VT011", "VT012", "VT013", "VT014",
                     "VT015")

# minimal trigger snippets, printed by ``vlint --explain VTxxx`` next to
# the rule's contract while burning down findings
_EXAMPLES = {
    "VT001": '''class SchedulerCache:
    def sneak(self, task):                 # no mark_*_dirty / _touched
        job = self.jobs[task.job]
        job.update_task_status(job.tasks[task.uid], "Releasing")''',
    "VT002": '''import time
def decide(job):
    return time.time() - job.creation_timestamp   # inject ssn.now()''',
    "VT003": '''import random
def pick(nodes):
    return random.choice(nodes)            # inject random.Random(seed)''',
    "VT004": '''def rogue(cache, task):
    cache.binder.bind(task, task.node_name)   # no _journal_intent''',
    "VT005": '''try:
    action()
except BaseException:                      # swallows SimKill
    pass''',
    "VT006": '''solver = _job_solver()
solver(state, tasks)                       # no _bucket()/pad on the path''',
    "VT007": '''class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []
    def record(self, ev):
        self.events.append(ev)             # write outside self._lock''',
    "VT008": '''def bind(self, task):
    seq = self._journal_intent("bind", task)   # intent never reads
    self.binder.bind(task, task.node_name)     # fencing_epoch()''',
    "VT009": '''def hand_over(pmap, node):
    pmap._transfer_node_raw(node, 2)       # no _journal_reserve record''',
    "VT019": '''def grow(pmap):
    pid = pmap._spawn_partition_raw()      # no partition_spawn record''',
    "VT020": '''def shed(self, ssn, task):
    ssn.evict(task, "elastic-scale")       # no elastic_shrink record:
                                           # replay can't tell a shrink
                                           # from a preemption''',
    "VT021": '''def heal(self, device):
    DEVICE_HEALTH.quarantine(device, "oom")   # no invalidate_device_state:
                                              # next dispatch reuses tensors
                                              # shaped for the dead mesh''',
    "VT022": '''def _journal_intent(self, op, task):
    self.journal.record_intent(op, task)   # no TIMELINE.stamp/record:
                                           # the durable record carries no
                                           # ctx — the job timeline breaks
                                           # at the next failover/move''',
    "VT010": '''packed = solver(state, tasks)          # device value
n = int(packed[0])                     # implicit fetch OUTSIDE any
                                       # solve/replay/upload span''',
    "VT011": '''def kernel(x):                         # jax.jit(kernel)
    if x > 0:                          # traced value in a Python branch
        return x''',
    "VT012": '''def run(f, xs):                        # f not named *solver*
    return f(xs)                       # ...but dataflow sees jax.jit
run(jax.jit(lambda x: x), xs)          # flows in; no bucket on path''',
    "VT013": '''idx = np.arange(n)                     # no dtype: weak int
solver(state, idx)                     # truncates under x64-disabled''',
    "VT014": '''class SchedulerCache:
    def remember(self, ssn):
        self._last_nodes = ssn.nodes   # outlives close_session''',
    "VT015": '''def _dispatch_speculation(self, rec, runnable):
    sssn = open_session(self.cache, speculative=True)
    ssn.cache.bind_batch(gang)         # journaled side effect BEFORE
                                       # the commit funnel''',
    "VT016": '''def flush(self, store, pg):
    store.update_status(pg)            # bare verb: first transient
                                       # apiserver error crashes the
                                       # cycle — ride the retrying
                                       # transport funnel''',
    "VT017": '''def rogue(self, task):
    seq = self._journal_intent("bind", task)
    self.binder.bind(task, task.node_name)   # no _register_inflight:
                                             # a lost kubelet ack wedges
                                             # this bind forever''',
    "VT018": '''def drain(self):
    for key, item in self.pending_work.items():   # no budget/limit
        self.retry(key, item)                     # witness: unbounded
                                                  # work per cycle''',
}
for _rule in ALL_RULES:
    _rule.example = _EXAMPLES.get(_rule.id, "")


def rule_by_id(rule_id: str) -> Optional[Rule]:
    for rule in ALL_RULES:
        if rule.id == rule_id:
            return rule
    return None
