"""vlint's interprocedural dataflow engine (stdlib ``ast`` only).

A lightweight abstract-value lattice tracks four taints through
assignments (incl. element-wise tuple unpacking), calls (parameter and
return summaries, iterated to a package-wide fixpoint), returns,
comprehensions and attribute chains:

- ``device``   the value is (or contains) a jax device array: produced by
               ``jnp.*`` / ``jax.device_put``, by invoking a jitted
               callable, or by ``Session.snapshot_node_tensors`` and the
               NodeTensors device getters. Feeding one into host-only
               code (``np.*``, ``float``/``int``/``bool``/``len``,
               ``.item()``, iteration, a branch test) forces a host↔device
               synchronization — the overlap blockers VT010 inventories.
- ``traced``   the value is a tracer: a parameter of a jit-entry function
               (minus ``static_argnames``). A Python ``if``/``while``/
               ``assert`` on one concretizes silently or retraces (VT011).
- ``session``  the value derives from an open scheduling Session (an
               ``ssn`` parameter, ``open_session``, a snapshot). Storing
               one where it outlives ``close_session`` is VT014's escape.
- ``jitfn``    the value is a compiled callable (``jax.jit`` result or a
               producer's return). CALLING it is a jit invocation — the
               site set VT006/VT012/VT013 police for shape bucketing and
               dtype discipline.
- ``weak``     the value is an ambient-dtype array (``np.arange`` /
               ``np.zeros``-family without an explicit dtype): weak-typed
               operands re-key jit compiles and truncate under disabled
               x64 when they reach a solver (VT013).

Design bias (same as the CallGraph's): the lattice is a MAY-analysis and
deliberately cheap — no aliasing, attribute taint is tracked by attribute
NAME package-wide, call summaries merge all same-named defs. A missing
edge costs a false positive (suppressible with a justification); the
approximations are chosen so they can only ADD taint, never hide it —
except where a rule uses context to EXCUSE a finding (VT010's
readback-span allowlist), which accepts the union bias and documents it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from .core import (AnalysisContext, FunctionInfo, ModuleInfo, dotted_name)

DEVICE = "device"
TRACED = "traced"
SESSION = "session"
JITFN = "jitfn"
WEAK = "weak"

# taints that flow through attribute READS by attribute name (tracked
# PER MODULE: a device array stored on self.X is a device array when read
# as obj.X anywhere in the same module — the _FusedSolution/_EvictTensors
# pattern; cross-module attr flow would alias unrelated names like
# ``.state`` into false positives). session flows through the BASE value
# instead (ssn.nodes is session because ssn is), and traced never enters
# object graphs in this codebase's kernels.
_ATTR_TAINTS = (DEVICE, JITFN, WEAK)

# value-taint dict: taint kind -> origin string ("where it came from")
TV = Dict[str, str]
# a return summary is either one TV or an element-wise tuple of TVs
RetVal = Union[TV, List[TV]]

_SESSION_PARAM_NAMES = {"ssn", "session", "sess"}

# numpy constructors whose dtype defaults to the ambient (weak) type;
# value = index of the positional argument that, when present, supplies
# the dtype explicitly
_WEAK_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "arange": 3, "full": 2}

# host builtins that force a device->host fetch when handed a device array
_HOST_CASTS = {"float", "int", "bool", "len", "list", "tuple", "sorted",
               "sum", "min", "max", "any", "all"}

# metadata attributes that are host/static even on device arrays/tracers
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "at"}

# methods that stay on device when called on a device array
_SYNC_METHODS = {"item", "tolist", "tobytes"}


def _merge(dst: TV, src: TV) -> bool:
    changed = False
    for k, v in src.items():
        if k not in dst:
            dst[k] = v
            changed = True
    return changed


def _union(*tvs: TV) -> TV:
    out: TV = {}
    for tv in tvs:
        _merge(out, tv)
    return out


def _strip(tv: TV, *kinds: str) -> TV:
    return {k: v for k, v in tv.items() if k not in kinds}


def _flat(val: Union[TV, List[TV], None]) -> TV:
    if val is None:
        return {}
    if isinstance(val, list):
        return _union(*val) if val else {}
    return val


@dataclass
class SyncSite:
    """One host↔device synchronization point: ``kind`` is the syncing
    operation, ``producer`` the expression the device taint came from —
    both go into the VT010 finding so the report doubles as the
    async-overlap worklist (docs/static-analysis.md)."""

    node: ast.AST
    kind: str
    producer: str


@dataclass
class JitCall:
    node: ast.Call
    desc: str                    # callee descriptor ("solver", "_job_solver()")
    # VT013 inputs: (arg node, arg descriptor, producer) for every operand
    # that is a bare numeric literal or carries the ``weak`` taint
    weak_args: List[Tuple[ast.AST, str, str]] = field(default_factory=list)


@dataclass
class FunctionFacts:
    sync_sites: List[SyncSite] = field(default_factory=list)
    jit_calls: List[JitCall] = field(default_factory=list)
    # (test node, producer) for traced-value branches in jit-entry code
    traced_tests: List[Tuple[ast.AST, str]] = field(default_factory=list)
    # (node, target descriptor, producer) for session-scoped values stored
    # where they outlive the session
    session_escapes: List[Tuple[ast.AST, str, str]] = \
        field(default_factory=list)


@dataclass
class _Summary:
    ret: Optional[RetVal] = None
    params: Dict[str, TV] = field(default_factory=dict)


class DataflowEngine:
    """Package-wide taint fixpoint + per-function fact extraction.

    Built once per analysis run (``get_dataflow``); rules read
    ``facts(fn)``. Rounds re-evaluate every function until parameter and
    return summaries stop growing (bounded), then one final collecting
    pass records the sites."""

    # safety cap only — the lattice is monotone and finite (taints and
    # summaries only grow), so the loop terminates by convergence; the
    # cap guards against an engine bug, not expected depth. If it were
    # ever hit, ``converged`` would read False and facts could be
    # missing taint — tests/test_analysis.py pins converged=True on the
    # real tree so CI notices before findings silently disappear.
    MAX_ROUNDS = 50

    def __init__(self, ctx: AnalysisContext):
        self.ctx = ctx
        # per-FunctionInfo summaries. Interprocedural propagation only
        # fires through UNAMBIGUOUS simple names (exactly one def in the
        # package, or a unique class name for __init__): a shared name
        # like ``get``/``add``/``step`` would alias every same-named
        # method's arguments into one summary and flood the lattice.
        self.summaries: Dict[int, _Summary] = {}
        # (module path, attribute name) -> taints (device/jitfn/weak only)
        self.attr_taints: Dict[Tuple[str, str], TV] = {}
        # simple names of functions whose bodies run traced (passed to
        # jax.jit / @jax.jit-decorated), with their static_argnames
        self.jit_entries: Set[str] = set()
        self.static_params: Dict[str, Set[str]] = {}
        # class simple name -> its __init__ FunctionInfo (None sentinel on
        # package-wide class-name collision)
        self.class_inits: Dict[str, Optional[FunctionInfo]] = {}
        # per-module: class names whose instances are session-scoped —
        # __init__ takes a session parameter, or the class is a plugin
        # (has on_session_open: the framework REBUILDS plugins every
        # open_session, docs/static-analysis.md) — storing session state
        # on them is not an escape
        self.session_classes: Dict[str, Set[str]] = {}
        self._module_globals: Dict[str, Set[str]] = {}
        # (module path, module-global name) -> taints stored into it
        # (via NAME[k] = v or global NAME = v): the _SOLVER_CACHE pattern
        self.global_taints: Dict[Tuple[str, str], TV] = {}
        self._facts: Dict[int, FunctionFacts] = {}
        self.converged = False
        self._prescan()
        self._traced_ctx = self._traced_contexts()
        for _ in range(self.MAX_ROUNDS):
            if not self._run_round(collect=False):
                self.converged = True
                break
        self._run_round(collect=True)

    def facts(self, fn: FunctionInfo) -> FunctionFacts:
        return self._facts.get(id(fn), FunctionFacts())

    # -- prescan ------------------------------------------------------------

    def _is_jit_factory(self, mod: ModuleInfo, node: ast.Call) -> bool:
        resolved = mod.resolve_call(node)
        return resolved in ("jax.jit", "jit")

    def _prescan(self) -> None:
        for mod in self.ctx.modules:
            # session-scoped classes: __init__ has an ssn/session param,
            # or the class is a per-session-rebuilt plugin
            scoped: Set[str] = set()
            for fn in mod.functions:
                if fn.cls is None:
                    continue
                if fn.name == "on_session_open":
                    scoped.add(fn.cls)
                if fn.name == "__init__":
                    args = {a.arg for a in fn.node.args.args}
                    args |= {a.arg for a in fn.node.args.kwonlyargs}
                    if args & _SESSION_PARAM_NAMES:
                        scoped.add(fn.cls)
                    if fn.cls in self.class_inits:
                        self.class_inits[fn.cls] = None   # ambiguous
                    else:
                        self.class_inits[fn.cls] = fn
            self.session_classes[mod.path] = scoped
            self._module_globals[mod.path] = _module_global_names(mod)
            for node in ast.walk(mod.tree):
                # jax.jit(f, static_argnames=...) / jax.jit(lambda..)
                if isinstance(node, ast.Call) \
                        and self._is_jit_factory(mod, node):
                    statics = _static_argnames(node)
                    for arg in node.args[:1]:
                        if isinstance(arg, ast.Name):
                            self.jit_entries.add(arg.id)
                            self.static_params.setdefault(
                                arg.id, set()).update(statics)
                # @jax.jit / @partial(jax.jit, ...) decorators
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        target = dec.func if isinstance(dec, ast.Call) \
                            else dec
                        dn = dotted_name(target) or ""
                        if dn.split(".")[-1] == "jit":
                            self.jit_entries.add(node.name)
                            if isinstance(dec, ast.Call):
                                self.static_params.setdefault(
                                    node.name, set()).update(
                                    _static_argnames(dec))
                        elif dn.split(".")[-1] == "partial" \
                                and isinstance(dec, ast.Call) and dec.args:
                            inner = dotted_name(dec.args[0]) or ""
                            if inner.split(".")[-1] == "jit":
                                self.jit_entries.add(node.name)
                                self.static_params.setdefault(
                                    node.name, set()).update(
                                    _static_argnames(dec))

    # -- fixpoint rounds ----------------------------------------------------

    def _run_round(self, collect: bool) -> bool:
        changed = False
        for mod in self.ctx.modules:
            for fn in mod.functions:
                ev = _FunctionEval(self, mod, fn, collect=collect)
                changed |= ev.run()
                if collect:
                    self._facts[id(fn)] = ev.facts
        return changed

    # -- traced contexts ----------------------------------------------------

    def _traced_contexts(self) -> Set[int]:
        """Functions whose bodies execute under a jax trace: jit-entry
        defs, everything lexically nested inside one, and helpers whose
        every caller is itself a traced context (kernel utilities like
        ops/place._select). Inside a traced context ``jnp.*`` values are
        tracers, not device arrays — a host-looking op there is traced by
        XLA, not a sync, so VT010 collection is suppressed."""
        out: Set[int] = set()
        all_fns = [fn for m in self.ctx.modules for fn in m.functions]
        by_qual: Dict[Tuple[str, str], FunctionInfo] = {
            (fn.module.path, fn.qualname): fn for fn in all_fns}
        for fn in all_fns:
            parts = set(fn.qualname.split("."))
            if fn.name in self.jit_entries or parts & self.jit_entries:
                out.add(id(fn))
        changed = True
        while changed:
            changed = False
            for fn in all_fns:
                if id(fn) in out:
                    continue
                # lexically nested inside a traced-context function
                parts = fn.qualname.split(".")
                for i in range(1, len(parts)):
                    anc = by_qual.get((fn.module.path,
                                       ".".join(parts[:i])))
                    if anc is not None and id(anc) in out:
                        out.add(id(fn))
                        changed = True
                        break
                if id(fn) in out:
                    continue
                # every caller runs traced (kernel helpers like _select)
                callers = self.ctx.graph.callers_of(fn)
                if callers and all(id(c) in out for c in callers):
                    out.add(id(fn))
                    changed = True
        return out

    # -- shared summary plumbing --------------------------------------------

    def resolve_callee(self, name: str,
                       method: bool) -> Optional[FunctionInfo]:
        """The unambiguous local def a call by simple ``name`` reaches:
        exactly one def in the package, or a unique class's __init__ for
        constructor calls. None blocks interprocedural propagation (the
        safe direction: a missed summary can only lose taint the fixture
        tests don't rely on, never invent it)."""
        defs = self.ctx.graph.defs.get(name)
        if defs is not None and len(defs) == 1:
            return defs[0]
        if not method and name in self.class_inits:
            return self.class_inits[name]
        return None

    def summary(self, fn: FunctionInfo) -> _Summary:
        s = self.summaries.get(id(fn))
        if s is None:
            s = self.summaries[id(fn)] = _Summary()
        return s

    def note_return(self, fn: FunctionInfo, val: RetVal) -> bool:
        s = self.summary(fn)
        if isinstance(val, list) and isinstance(s.ret, list) \
                and len(val) == len(s.ret):
            changed = False
            for dst, src in zip(s.ret, val):
                changed |= _merge(dst, src)
            return changed
        if s.ret is None:
            s.ret = [dict(tv) for tv in val] if isinstance(val, list) \
                else dict(val)
            return bool(_flat(s.ret))
        # shape mismatch across return statements: collapse to one TV
        merged = _union(_flat(s.ret), _flat(val))
        if merged != _flat(s.ret) or isinstance(s.ret, list):
            s.ret = merged
            return True
        return False

    def note_param(self, fn: FunctionInfo, param: str, tv: TV) -> bool:
        if not tv:
            return False
        s = self.summary(fn)
        dst = s.params.setdefault(param, {})
        return _merge(dst, tv)

    def note_global(self, mod_path: str, name: str, tv: TV) -> bool:
        kept = _strip(tv, TRACED)
        if not kept:
            return False
        dst = self.global_taints.setdefault((mod_path, name), {})
        return _merge(dst, kept)

    def note_attr(self, mod_path: str, attr: str, tv: TV) -> bool:
        kept = {k: v for k, v in tv.items() if k in _ATTR_TAINTS}
        if not kept:
            return False
        dst = self.attr_taints.setdefault((mod_path, attr), {})
        return _merge(dst, kept)


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            out: Set[str] = set()
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, str):
                    out.add(el.value)
            return out
    return set()


def _module_global_names(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            out.add(node.target.id)
    return out


class _FunctionEval:
    """Abstract interpretation of one function body.

    Two internal passes per round (so loop-carried taints reach first-use
    sites) with facts collected only on the engine's final collecting
    round — no duplicate findings, stable environments."""

    def __init__(self, engine: DataflowEngine, mod: ModuleInfo,
                 fn: FunctionInfo, collect: bool):
        self.eng = engine
        self.mod = mod
        self.fn = fn
        self.collect = collect
        self.facts = FunctionFacts()
        self.env: Dict[str, TV] = {}
        self.globals_decl: Set[str] = set()
        self.changed = False
        self._recording = False
        self.ret_val: Optional[RetVal] = None

    # -- entry --------------------------------------------------------------

    def run(self) -> bool:
        self._seed_params()
        for final in (False, True):
            self._recording = self.collect and final
            for stmt in self.fn.node.body:
                self.stmt(stmt)
        if self.ret_val is not None:
            self.changed |= self.eng.note_return(self.fn, self.ret_val)
        return self.changed

    def _loc(self, node: ast.AST) -> str:
        return f"{self.mod.path}:{getattr(node, 'lineno', 0)}"

    def _seed_params(self) -> None:
        args = self.fn.node.args
        names = [a.arg for a in args.posonlyargs + args.args
                 + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        summary = self.eng.summaries.get(id(self.fn))
        is_jit_entry = self.fn.name in self.eng.jit_entries
        statics = self.eng.static_params.get(self.fn.name, set())
        for name in names:
            tv: TV = {}
            if name in _SESSION_PARAM_NAMES:
                tv[SESSION] = f"parameter {name!r}"
            if "solver" in name:
                tv[JITFN] = f"solver-valued parameter {name!r}"
            if is_jit_entry and name not in statics and name != "self":
                tv[TRACED] = (f"traced parameter {name!r} of jit-entry "
                              f"{self.fn.name}")
            if summary is not None and name in summary.params:
                tv = _union(tv, summary.params[name])
            if tv:
                self.env[name] = tv

    # -- fact recording -----------------------------------------------------

    def _sync(self, node: ast.AST, kind: str, tv: TV) -> None:
        if id(self.fn) in self.eng._traced_ctx:
            return          # tracer ops inside a jit trace are not syncs
        if self._recording:
            self.facts.sync_sites.append(SyncSite(
                node=node, kind=kind, producer=tv.get(DEVICE, "?")))

    def _traced_test(self, node: ast.AST, tv: TV) -> None:
        if self._recording:
            self.facts.traced_tests.append((node, tv.get(TRACED, "?")))

    def _escape(self, node: ast.AST, target: str, tv: TV) -> None:
        if self._recording:
            self.facts.session_escapes.append(
                (node, target, tv.get(SESSION, "?")))

    # -- statements ---------------------------------------------------------

    def stmt(self, node: ast.stmt) -> None:
        m = getattr(self, "stmt_" + type(node).__name__, None)
        if m is not None:
            m(node)
            return
        # default: evaluate embedded expressions, walk nested bodies
        for fname in ("body", "orelse", "finalbody"):
            for sub in getattr(node, fname, []) or []:
                self.stmt(sub)
        for h in getattr(node, "handlers", []) or []:
            for sub in h.body:
                self.stmt(sub)

    def stmt_Global(self, node: ast.Global) -> None:
        self.globals_decl.update(node.names)

    def stmt_Expr(self, node: ast.Expr) -> None:
        self.ev(node.value)

    def stmt_Return(self, node: ast.Return) -> None:
        if node.value is None:
            return
        if isinstance(node.value, ast.Tuple):
            val: RetVal = [self.ev(el) for el in node.value.elts]
        else:
            v = self.ev(node.value)
            val = v if not isinstance(v, list) else v
        if self.ret_val is None:
            self.ret_val = val
        elif isinstance(self.ret_val, list) and isinstance(val, list) \
                and len(val) == len(self.ret_val):
            for dst, src in zip(self.ret_val, val):
                _merge(dst, src)
        else:
            self.ret_val = _union(_flat(self.ret_val), _flat(val))

    def _assign_name(self, node: ast.AST, name: str, tv: TV) -> None:
        if name in self.globals_decl:
            self.changed |= self.eng.note_global(self.mod.path, name, tv)
            if SESSION in tv:
                self._escape(node, f"module global {name!r}", tv)
        if tv:
            # OVERWRITE, do not union: a rebind kills the old taint —
            # ``x = jax.device_get(x)`` must leave x host. Loop-carried
            # taints are handled by the two-pass body evaluation, not by
            # making the environment sticky.
            self.env[name] = dict(tv)
        elif name in self.env:
            del self.env[name]

    def _assign_target(self, stmt: ast.AST, tgt: ast.expr,
                       val: Union[TV, List[TV]]) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            if isinstance(val, list) and len(val) == len(tgt.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in tgt.elts):
                for el, v in zip(tgt.elts, val):
                    self._assign_target(stmt, el, v)
            else:
                flat = _flat(val)
                for el in tgt.elts:
                    self._assign_target(
                        stmt, el.value if isinstance(el, ast.Starred)
                        else el, flat)
            return
        tv = _flat(val)
        if isinstance(tgt, ast.Name):
            self._assign_name(stmt, tgt.id, tv)
            return
        if isinstance(tgt, ast.Attribute):
            self.changed |= self.eng.note_attr(self.mod.path, tgt.attr, tv)
            base = dotted_name(tgt.value)
            if SESSION in tv and base == "self" \
                    and self.fn.cls is not None \
                    and self.fn.cls not in self.eng.session_classes.get(
                        self.mod.path, set()):
                self._escape(stmt, f"self.{tgt.attr} "
                             f"(class {self.fn.cls} is not "
                             f"session-scoped)", tv)
            return
        if isinstance(tgt, ast.Subscript):
            # store into a module-global container: an escape for session
            # values (the container outlives the cycle)
            base = tgt.value
            while isinstance(base, ast.Subscript):
                base = base.value
            self.ev(tgt.slice)
            if isinstance(base, ast.Name) \
                    and base.id in self.eng._module_globals.get(
                        self.mod.path, set()) \
                    and base.id not in self.env:
                self.changed |= self.eng.note_global(
                    self.mod.path, base.id, tv)
                if SESSION in tv:
                    self._escape(stmt, f"module-global container "
                                 f"{base.id!r}", tv)

    def stmt_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Tuple):
            val: Union[TV, List[TV]] = [self.ev(el)
                                        for el in node.value.elts]
        else:
            val = self.ev_maybe_tuple(node.value)
        for tgt in node.targets:
            self._assign_target(node, tgt, val)

    def stmt_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is None:
            return
        self._assign_target(node, node.target,
                            self.ev_maybe_tuple(node.value))

    def stmt_AugAssign(self, node: ast.AugAssign) -> None:
        tv = _union(self.ev(node.value),
                    self.ev(ast.copy_location(
                        ast.Name(id=node.target.id, ctx=ast.Load()),
                        node.target))
                    if isinstance(node.target, ast.Name) else {})
        self._assign_target(node, node.target, tv)

    def stmt_For(self, node: ast.For) -> None:
        it = self.ev(node.iter)
        if DEVICE in it and not _container_iter(node.iter):
            self._sync(node.iter, "iteration", it)
        elt = _strip(it, JITFN)
        self._assign_target(node, node.target, elt)
        for sub in node.body:
            self.stmt(sub)
        for sub in node.orelse:
            self.stmt(sub)

    def _test(self, node: ast.expr) -> None:
        tv = self.ev(node)
        if DEVICE in tv and not _static_test(node):
            self._sync(node, "branch-test", tv)
        if TRACED in tv and self.fn.name in self.eng.jit_entries \
                and not _static_test(node):
            self._traced_test(node, tv)

    def stmt_If(self, node: ast.If) -> None:
        self._test(node.test)
        for sub in node.body:
            self.stmt(sub)
        for sub in node.orelse:
            self.stmt(sub)

    def stmt_While(self, node: ast.While) -> None:
        self._test(node.test)
        for sub in node.body:
            self.stmt(sub)
        for sub in node.orelse:
            self.stmt(sub)

    def stmt_Assert(self, node: ast.Assert) -> None:
        self._test(node.test)
        if node.msg is not None:
            self.ev(node.msg)

    def stmt_With(self, node: ast.With) -> None:
        for item in node.items:
            tv = self.ev(item.context_expr)
            if item.optional_vars is not None:
                self._assign_target(node, item.optional_vars, tv)
        for sub in node.body:
            self.stmt(sub)

    stmt_AsyncWith = stmt_With

    def stmt_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def closing over tainted locals is a value carrying
        # those taints (the closure half of VT014): bind its name to the
        # union of the tainted free names it references
        tv: TV = {}
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.env:
                _merge(tv, _strip(self.env[sub.id], TRACED))
        if tv:
            self.env[node.name] = tv

    stmt_AsyncFunctionDef = stmt_FunctionDef

    def stmt_Raise(self, node: ast.Raise) -> None:
        if node.exc is not None:
            self.ev(node.exc)

    def stmt_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                self.env.pop(tgt.id, None)

    # -- expressions --------------------------------------------------------

    def ev_maybe_tuple(self, node: ast.expr) -> Union[TV, List[TV]]:
        """Like ``ev`` but preserves element-wise taints for calls whose
        summaries are tuples — so ``a, b = helper()`` distributes."""
        if isinstance(node, ast.Call):
            tv = self.ev(node, want_tuple=True)
            return tv
        if isinstance(node, ast.Tuple):
            return [self.ev(el) for el in node.elts]
        return self.ev(node)

    def ev(self, node: ast.expr,
           want_tuple: bool = False) -> Union[TV, List[TV]]:
        out = self._ev(node, want_tuple)
        return out

    def _ev(self, node: ast.expr, want_tuple: bool = False):
        if isinstance(node, ast.Name):
            tv = self.env.get(node.id)
            if tv is not None:
                return dict(tv)
            gtv = self.eng.global_taints.get((self.mod.path, node.id))
            return dict(gtv) if gtv else {}
        if isinstance(node, ast.Constant):
            return {}
        if isinstance(node, ast.Attribute):
            base = self.ev(node.value)
            if node.attr in _STATIC_ATTRS:
                return {}
            out = {k: v for k, v in base.items()
                   if k in (SESSION, TRACED)}
            attr_tv = self.eng.attr_taints.get((self.mod.path, node.attr))
            if attr_tv:
                _merge(out, dict(attr_tv))
            return out
        if isinstance(node, ast.Call):
            return self._ev_call(node, want_tuple)
        if isinstance(node, ast.Subscript):
            base = self.ev(node.value)
            self.ev(node.slice)
            if isinstance(base, list):
                base = _flat(base)
            return base                 # element reads keep jitfn: caches
        if isinstance(node, (ast.BinOp,)):
            return _union(self.ev(node.left), self.ev(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.ev(node.operand)
        if isinstance(node, ast.BoolOp):
            return _union(*[self.ev(v) for v in node.values])
        if isinstance(node, ast.Compare):
            tv = _union(self.ev(node.left),
                        *[self.ev(c) for c in node.comparators])
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in node.ops):
                return {}                    # identity/membership: host bool
            return tv
        if isinstance(node, ast.IfExp):
            self._test(node.test)
            return _union(self.ev(node.body), self.ev(node.orelse))
        if isinstance(node, ast.Tuple):
            if want_tuple:
                return [self.ev(el) for el in node.elts]
            return _union(*[self.ev(el) for el in node.elts])
        if isinstance(node, (ast.List, ast.Set)):
            return _union(*[self.ev(el) for el in node.elts])
        if isinstance(node, ast.Dict):
            vals = [self.ev(v) for v in node.values if v is not None]
            for k in node.keys:
                if k is not None:
                    self.ev(k)
            return _union(*vals) if vals else {}
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            return self._ev_comp(node)
        if isinstance(node, ast.Starred):
            return self.ev(node.value)
        if isinstance(node, ast.Lambda):
            tv: TV = {}
            for sub in ast.walk(node.body):
                if isinstance(sub, ast.Name) and sub.id in self.env:
                    _merge(tv, _strip(self.env[sub.id], TRACED))
            return tv
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.ev(v.value)
            return {}
        if isinstance(node, ast.FormattedValue):
            self.ev(node.value)
            return {}
        if isinstance(node, ast.Await):
            return self.ev(node.value)
        if isinstance(node, ast.NamedExpr):
            tv = self.ev(node.value)
            self._assign_name(node, node.target.id, _flat(tv))
            return tv
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self.ev(part)
            return {}
        return {}

    def _ev_comp(self, node) -> TV:
        saved: Dict[str, Optional[TV]] = {}
        for gen in node.generators:
            it = self.ev(gen.iter)
            if DEVICE in it and not _container_iter(gen.iter):
                self._sync(gen.iter, "iteration", it)
            elt = _strip(it, JITFN)
            for name in _target_names(gen.target):
                saved.setdefault(name, self.env.get(name))
                if elt:
                    self.env[name] = _union(self.env.get(name, {}), elt)
            for cond in gen.ifs:
                self._test(cond)
        if isinstance(node, ast.DictComp):
            out = _union(self.ev(node.key), self.ev(node.value))
        else:
            out = self.ev(node.elt)
        for name, old in saved.items():
            if old is None:
                self.env.pop(name, None)
            else:
                self.env[name] = old
        return out

    # -- calls --------------------------------------------------------------

    def _ev_call(self, node: ast.Call, want_tuple: bool = False):
        arg_tvs = [_flat(self.ev(a)) for a in node.args]
        # positional list FIRST (two **expansions share arg=None — a dict
        # would collapse them and misalign taint attribution), dict view
        # for named-parameter threading
        kw_tv_list = [_flat(self.ev(kw.value)) for kw in node.keywords]
        kw_tvs = {kw.arg: tv for kw, tv in zip(node.keywords, kw_tv_list)
                  if kw.arg}
        resolved = self.mod.resolve_call(node) or ""
        parts = resolved.split(".")
        head = parts[0]
        func = node.func
        callee_desc = dotted_name(func) or "<expr>"
        all_args = list(arg_tvs) + list(kw_tvs.values())

        # jax.jit(...) minting a compiled callable
        if resolved in ("jax.jit", "jit"):
            return {JITFN: f"jax.jit(...) at {self._loc(node)}"}

        # jax.numpy.* — device-array producers (and traced/session carry)
        if head == "jax" and len(parts) >= 2 and parts[1] == "numpy":
            out = _union(*all_args) if all_args else {}
            out = _strip(out, JITFN, WEAK)
            out[DEVICE] = f"{_short(resolved)}(...) at {self._loc(node)}"
            if parts[-1] in _WEAK_CTORS and not _has_dtype(
                    node, _WEAK_CTORS[parts[-1]]):
                out[WEAK] = (f"{_short(resolved)}(...) without dtype at "
                             f"{self._loc(node)}")
            return out

        if resolved == "jax.device_put":
            out = _union(*all_args) if all_args else {}
            out = _strip(out, JITFN)
            out[DEVICE] = f"jax.device_put(...) at {self._loc(node)}"
            return out

        if resolved in ("jax.device_get", "jax.block_until_ready"):
            merged = _union(*all_args) if all_args else {}
            if DEVICE in merged:
                self._sync(node, _short(resolved), merged)
            return _strip(merged, DEVICE, JITFN)

        # numpy.* on a device operand is an implicit device_get
        if head == "numpy":
            merged = _union(*all_args) if all_args else {}
            if DEVICE in merged:
                self._sync(node, _short(resolved), merged)
            out = _strip(merged, DEVICE, JITFN)
            tail = parts[-1] if len(parts) > 1 else ""
            if tail in _WEAK_CTORS and not _has_dtype(
                    node, _WEAK_CTORS[tail]):
                out[WEAK] = (f"np.{tail}(...) without dtype at "
                             f"{self._loc(node)}")
            elif "dtype" in kw_tvs or tail in ("asarray", "astype"):
                out = _strip(out, WEAK) if _has_dtype(node, 1) else out
            return out

        # host builtins force the fetch
        if isinstance(func, ast.Name) and func.id in _HOST_CASTS \
                and func.id not in self.env:
            merged = _union(*all_args) if all_args else {}
            if DEVICE in merged:
                self._sync(node, f"{func.id}()", merged)
            return _strip(merged, DEVICE, JITFN, SESSION, TRACED) \
                if func.id in ("float", "int", "bool", "len") \
                else _strip(merged, DEVICE, JITFN)

        # method calls ------------------------------------------------------
        if isinstance(func, ast.Attribute):
            recv = _flat(self.ev(func.value))
            merged_args = _union(*all_args) if all_args else {}
            if func.attr == "snapshot_node_tensors":
                # the NodeTensors OBJECT is session-scoped host state; its
                # device residency is behind node_state()/device_* below
                return {SESSION: f"snapshot_node_tensors() at "
                                 f"{self._loc(node)}"}
            if func.attr in ("node_state", "device_allocatable",
                             "device_max_tasks"):
                return _union(_strip(recv, DEVICE),
                              {DEVICE: f"{callee_desc}() at "
                                       f"{self._loc(node)}"})
            if func.attr in _SYNC_METHODS and DEVICE in recv:
                self._sync(node, f".{func.attr}()", recv)
                return _strip(recv, DEVICE, JITFN)
            if func.attr == "astype":
                return _strip(_union(recv, merged_args), WEAK, JITFN)
            # invoking a jit-valued attribute (self._solve(...)); a jitfn
            # merely HELD by the receiver (a cache dict) is not invoked by
            # calling one of the receiver's own methods
            if func.attr in self._module_jit_attrs():
                return self._jit_invoke(node, callee_desc, arg_tvs, kw_tv_list)
            # local def reachable as a method: thread param taints.
            # _AMBIENT_METHODS never consult a summary either — `reshape`
            # having one def somewhere in the package must not wipe a
            # device receiver's taint (the MAY invariant: approximations
            # may ADD taint, never hide it)
            self._note_callsite(func.attr, node, arg_tvs, kw_tvs,
                                method=True)
            if func.attr not in self._AMBIENT_METHODS:
                out = self._summary_ret(func.attr, want_tuple, method=True)
                if out is not None:
                    return out
            # unknown method: device receivers stay device (x.min(),
            # x.reshape()); session receivers derive session values
            out = _strip(_union(recv, merged_args), JITFN, WEAK)
            return out

        # plain-name calls --------------------------------------------------
        if isinstance(func, ast.Name):
            name = func.id
            bound = self.env.get(name)
            if bound and JITFN in bound:
                return self._jit_invoke(node, name, arg_tvs, kw_tv_list)
            carried = _strip(bound or {}, JITFN, DEVICE, WEAK)
            if name == "open_session":
                return {SESSION: f"open_session() at {self._loc(node)}"}
            self._note_callsite(name, node, arg_tvs, kw_tvs, method=False)
            out = self._summary_ret(name, want_tuple)
            if out is not None:
                if carried:
                    out = _union(_flat(out), carried) \
                        if not isinstance(out, list) else out
                return out
            merged = _union(*all_args) if all_args else {}
            return _union(_strip(merged, JITFN), carried)

        # calling the result of a call: producer()(args) — a jit
        # invocation when the inner call yields a compiled callable
        if isinstance(func, ast.Call):
            inner = _flat(self.ev(func))
            if JITFN in inner:
                return self._jit_invoke(
                    node, (dotted_name(func.func) or "<expr>") + "()",
                    arg_tvs, kw_tv_list)
            merged = _union(*all_args) if all_args else {}
            return _strip(merged, JITFN)

        merged = _union(*all_args) if all_args else {}
        return _strip(merged, JITFN)

    def _module_jit_attrs(self) -> Set[str]:
        return {a for (p, a), tv in self.eng.attr_taints.items()
                if p == self.mod.path and JITFN in tv}

    def _jit_invoke(self, node: ast.Call, desc: str,
                    arg_tvs: List[TV], kw_tv_list: List[TV]) -> TV:
        if self._recording:
            jc = JitCall(node=node, desc=desc)
            for arg, tv in zip(node.args, arg_tvs):
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, (int, float)) \
                        and not isinstance(arg.value, bool):
                    jc.weak_args.append(
                        (arg, repr(arg.value), "bare Python literal"))
                elif WEAK in tv:
                    jc.weak_args.append(
                        (arg, ast.unparse(arg)[:60] if hasattr(
                            ast, "unparse") else "<arg>", tv[WEAK]))
            for kw, tv in zip(node.keywords, kw_tv_list):
                if WEAK in tv:
                    jc.weak_args.append(
                        (kw.value, f"{kw.arg or '**'}=...", tv[WEAK]))
            self.facts.jit_calls.append(jc)
        return {DEVICE: f"jitted call {desc}(...) at {self._loc(node)}"}

    # method names jax arrays / stdlib containers also expose: a
    # ``dev.at[i].set(x)`` must not thread taints into Resource.set just
    # because ``set`` happens to have one def in the package
    _AMBIENT_METHODS = {"set", "get", "add", "sub", "update", "pop",
                        "clear", "copy", "keys", "values", "items",
                        "append", "extend", "remove", "sort", "min",
                        "max", "sum", "all", "any", "reshape", "astype"}

    def _note_callsite(self, name: str, node: ast.Call,
                       arg_tvs: List[TV], kw_tvs: Dict[str, TV],
                       method: bool) -> None:
        """Thread argument taints into a local def's parameter summary
        (the interprocedural half of the lattice)."""
        if method and name in self._AMBIENT_METHODS:
            return
        callee = self.eng.resolve_callee(name, method=method)
        if callee is None:
            return
        args = callee.node.args
        params = [a.arg for a in args.posonlyargs + args.args]
        if params and params[0] == "self":
            params = params[1:]
        for i, tv in enumerate(arg_tvs):
            if i < len(params):
                self.changed |= self.eng.note_param(callee, params[i], tv)
        kwonly = {a.arg for a in args.kwonlyargs}
        for kwname, tv in kw_tvs.items():
            if kwname and (kwname in params or kwname in kwonly):
                self.changed |= self.eng.note_param(callee, kwname, tv)

    def _summary_ret(self, name: str, want_tuple: bool,
                     method: bool = False):
        callee = self.eng.resolve_callee(name, method=method)
        if callee is None:
            return None if name not in self.eng.ctx.graph.defs else {}
        s = self.eng.summaries.get(id(callee))
        if s is None or s.ret is None:
            return {}
        if isinstance(s.ret, list):
            if want_tuple:
                return [dict(tv) for tv in s.ret]
            return _flat(s.ret)
        return dict(s.ret)


_CONTAINER_FNS = {"zip", "enumerate", "reversed", "map", "filter",
                  "range", "sorted"}


def _container_iter(node: ast.expr) -> bool:
    """Iterating zip()/enumerate()/... over device arrays walks a host
    container whose ELEMENTS are device arrays — structural, no fetch;
    only iterating a device array itself syncs."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in _CONTAINER_FNS)


def _target_names(tgt: ast.expr) -> List[str]:
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out: List[str] = []
        for el in tgt.elts:
            out.extend(_target_names(el))
        return out
    return []


def _static_test(node: ast.expr) -> bool:
    """Tests that are safe on tracers: identity against None and
    isinstance checks concretize nothing."""
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return True
    if isinstance(node, ast.Call):
        dn = dotted_name(node.func) or ""
        if dn.split(".")[-1] in ("isinstance", "hasattr", "callable"):
            return True
    return False


def _has_dtype(node: ast.Call, dtype_pos: int) -> bool:
    if any(kw.arg == "dtype" for kw in node.keywords):
        return True
    return len(node.args) > dtype_pos


def _short(resolved: str) -> str:
    return resolved.replace("jax.numpy.", "jnp.").replace("numpy.", "np.")


def get_dataflow(ctx: AnalysisContext) -> DataflowEngine:
    """The per-run engine, built lazily and cached on the context so the
    five dataflow rules share one fixpoint."""
    eng = getattr(ctx, "_dataflow", None)
    if eng is None:
        eng = DataflowEngine(ctx)
        ctx._dataflow = eng                      # type: ignore[attr-defined]
    return eng
