"""``vlint --diff BASE``: restrict findings to functions whose bodies
changed vs a git ref.

Pure stdlib: ``git diff --unified=0 BASE -- '*.py'`` is parsed for
post-image hunk ranges, and a finding survives when its ENCLOSING
FUNCTION's lexical span intersects a changed range (module-level
findings match on their own line). The full-tree pass stays the CI hard
gate; --diff keeps the edit-compile-lint loop fast as the tree grows —
it can only ever REMOVE findings, never add them, so a clean --diff run
is necessary but not sufficient.
"""

from __future__ import annotations

import os
import re
import subprocess
from typing import Dict, Iterable, List, Tuple

from .core import AnalysisContext, Finding, normalize_path

# ``@@ -12,3 +14,6 @@`` — we only need the post-image (+) side
_HUNK_RE = re.compile(r"^@@ -\d+(?:,\d+)? \+(?P<start>\d+)(?:,(?P<count>\d+))? @@")


class DiffError(RuntimeError):
    """git unavailable / bad ref — the CLI reports and exits 2."""


def changed_ranges(base: str, cwd: str = ".") -> Dict[str, List[Tuple[int, int]]]:
    """normalized path -> [(start, end)] 1-based inclusive line ranges
    that differ from ``base`` (post-image side; pure deletions collapse
    to a zero-length range at the deletion point so a finding ON the
    surrounding function still matches via its span)."""
    try:
        proc = subprocess.run(
            ["git", "diff", "--unified=0", "--no-color", base, "--",
             "*.py"],
            cwd=cwd, capture_output=True, text=True)
    except OSError as exc:  # pragma: no cover - no git binary
        raise DiffError(f"git not available: {exc}") from exc
    if proc.returncode not in (0, 1):
        raise DiffError(f"git diff {base!r} failed: "
                        f"{proc.stderr.strip() or proc.stdout.strip()}")
    ranges: Dict[str, List[Tuple[int, int]]] = {}
    current: str = ""
    for line in proc.stdout.splitlines():
        if line.startswith("+++ "):
            target = line[4:].strip()
            if target == "/dev/null":
                current = ""
                continue
            if target.startswith("b/"):
                target = target[2:]
            current = normalize_path(target)
            continue
        m = _HUNK_RE.match(line)
        if m and current:
            start = int(m.group("start"))
            count = int(m.group("count") or "1")
            end = start + max(count - 1, 0)
            ranges.setdefault(current, []).append((start, end))
    return ranges


def _overlaps(a_start: int, a_end: int, b_start: int, b_end: int) -> bool:
    return a_start <= b_end and b_start <= a_end


def restrict_findings(findings: Iterable[Finding], ctx: AnalysisContext,
                      ranges: Dict[str, List[Tuple[int, int]]]
                      ) -> Tuple[List[Finding], int]:
    """(kept, dropped_count): a finding is kept when its enclosing
    function's span — or, module-level, its own line — intersects a
    changed range of its file."""
    kept: List[Finding] = []
    dropped = 0
    for f in findings:
        file_ranges = ranges.get(f.path)
        if not file_ranges:
            dropped += 1
            continue
        mod = ctx.by_path.get(f.path)
        fn = mod.enclosing_function(f.line) if mod is not None else None
        if fn is not None:
            span = (fn.node.lineno,
                    getattr(fn.node, "end_lineno", fn.node.lineno))
        else:
            span = (f.line, f.line)
        if any(_overlaps(span[0], span[1], lo, hi)
               for lo, hi in file_ranges):
            kept.append(f)
        else:
            dropped += 1
    return kept, dropped


def repo_root_for(paths: List[str]) -> str:
    """cwd for the git invocation: the first existing path's directory
    (git resolves the repo root upward from there)."""
    for p in paths:
        if os.path.isdir(p):
            return p
        if os.path.exists(p):
            return os.path.dirname(os.path.abspath(p)) or "."
    return "."
