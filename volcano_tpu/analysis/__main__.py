"""``python -m volcano_tpu.analysis`` / ``vlint`` — the CLI.

Usage:
    vlint [paths...] [--format text|json] [--baseline FILE]
          [--no-baseline] [--update-baseline] [--rule VTxxx [...]]
          [--list-rules]

Exit codes: 0 clean (suppressed/baselined findings do not gate),
1 blocking findings or invalid suppressions, 2 usage/baseline errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .baseline import (DEFAULT_BASELINE, Baseline, BaselineError,
                       load_baseline, write_baseline)
from .core import analyze_paths
from .report import exit_code, json_report, split_baselined, text_report
from .rules import ALL_RULES, rule_by_id


def _default_paths() -> List[str]:
    """Default target: the volcano_tpu package next to this file (works
    from any cwd)."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _find_baseline(paths: List[str]) -> Optional[str]:
    """The checked-in baseline lives at the repo root (the package's
    parent); fall back to cwd."""
    for base in paths:
        probe = base if os.path.isdir(base) else os.path.dirname(base)
        for candidate in (os.path.join(os.path.dirname(
                os.path.abspath(probe)), DEFAULT_BASELINE),
                os.path.join(probe, DEFAULT_BASELINE)):
            if os.path.exists(candidate):
                return candidate
    cwd = os.path.join(os.getcwd(), DEFAULT_BASELINE)
    return cwd if os.path.exists(cwd) else None


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="vlint",
        description="contract-aware static analysis for volcano_tpu "
                    "(docs/static-analysis.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the volcano_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE} "
                             f"at the repo root when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(preserving existing justifications)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="VTxxx", help="run only these rules")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.name}: {rule.contract}")
        return 0

    rules = ALL_RULES
    if args.rule:
        rules = []
        for rid in args.rule:
            rule = rule_by_id(rid)
            if rule is None:
                print(f"vlint: unknown rule {rid!r} (--list-rules)",
                      file=sys.stderr)
                return 2
            rules.append(rule)

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"vlint: no such path: {p}", file=sys.stderr)
            return 2

    findings, invalid, _ = analyze_paths(paths, rules=rules)

    baseline_path = None if args.no_baseline else (
        args.baseline or _find_baseline(paths))
    try:
        baseline = load_baseline(baseline_path)
    except BaselineError as exc:
        print(f"vlint: {exc}", file=sys.stderr)
        return 2

    live, grandfathered = split_baselined(findings, baseline)

    if args.update_baseline:
        target = baseline_path or os.path.join(os.getcwd(),
                                               DEFAULT_BASELINE)
        merged = live + grandfathered
        write_baseline(target, merged, justifications={
            key: entry["justification"]
            for key, entry in baseline.entries.items()
            if entry.get("justification")})
        print(f"vlint: wrote {len(merged)} entr"
              f"{'y' if len(merged) == 1 else 'ies'} to {target}; "
              f"replace any TODO justifications before committing")
        return 0

    if args.format == "json":
        print(json_report(live, invalid, grandfathered, baseline))
    else:
        print(text_report(live, invalid, grandfathered, baseline))
    return exit_code(live, invalid)


if __name__ == "__main__":
    sys.exit(main())
