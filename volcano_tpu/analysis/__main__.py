"""``python -m volcano_tpu.analysis`` / ``vlint`` — the CLI.

Usage:
    vlint [paths...] [--format text|json|sarif] [--baseline FILE]
          [--no-baseline] [--update-baseline]
          [--rule VTxxx [...]] [--rules VTxxx,VTyyy] [--dataflow]
          [--diff BASE] [--explain VTxxx]
          [--sync-inventory [--sync-budget N]] [--list-rules]

Exit codes: 0 clean (suppressed/baselined findings do not gate),
1 blocking findings or invalid suppressions, 2 usage/baseline/diff
errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .baseline import (DEFAULT_BASELINE, Baseline, BaselineError,
                       load_baseline, write_baseline)
from .core import analyze_paths
from .report import (exit_code, json_report, sarif_report, split_baselined,
                     text_report)
from .rules import ALL_RULES, DATAFLOW_RULE_IDS, HostSyncRule, rule_by_id


def _default_paths() -> List[str]:
    """Default target: the volcano_tpu package next to this file (works
    from any cwd)."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _find_baseline(paths: List[str]) -> Optional[str]:
    """The checked-in baseline lives at the repo root (the package's
    parent); fall back to cwd."""
    for base in paths:
        probe = base if os.path.isdir(base) else os.path.dirname(base)
        for candidate in (os.path.join(os.path.dirname(
                os.path.abspath(probe)), DEFAULT_BASELINE),
                os.path.join(probe, DEFAULT_BASELINE)):
            if os.path.exists(candidate):
                return candidate
    cwd = os.path.join(os.getcwd(), DEFAULT_BASELINE)
    return cwd if os.path.exists(cwd) else None


def _explain(rule_id: str) -> int:
    rule = rule_by_id(rule_id)
    if rule is None:
        print(f"vlint: unknown rule {rule_id!r} (--list-rules)",
              file=sys.stderr)
        return 2
    print(f"{rule.id}  {rule.name}")
    print(f"contract: {rule.contract}")
    if rule.scope:
        print(f"scope:    {', '.join(rule.scope)}")
    if rule.exclude:
        print(f"exempt:   {', '.join(rule.exclude)}")
    doc = (rule.__doc__ or "").strip()
    if doc:
        print()
        print(doc)
    if rule.example:
        print()
        print("minimal trigger:")
        for line in rule.example.splitlines():
            print(f"    {line}")
    return 0


def _sync_inventory(paths: List[str],
                    budget: Optional[int] = None) -> int:
    """Print EVERY host-sync site the dataflow engine sees — excused or
    not — with its producer and why it is (or is not) allowlisted. This
    is the async-overlap worklist of ROADMAP item 2: the non-excused
    rows block solve/commit overlap today; the span-excused rows are the
    sanctioned fetch points the overlap redesign must double-buffer."""
    from .dataflow import get_dataflow
    # rules=[] — the inventory needs only the context + taint engine,
    # not 14 rule passes whose findings would be discarded
    _, _, ctx = analyze_paths(paths, rules=[])
    df = get_dataflow(ctx)
    rule = HostSyncRule()
    rows = []
    for mod in ctx.modules:
        for fn in mod.functions:
            for site in df.facts(fn).sync_sites:
                line = getattr(site.node, "lineno", fn.node.lineno)
                # the SAME excusal ladder CI gates on (HostSyncRule
                # .classify) — the inventory cannot drift from the rule
                status, detail = rule.classify(mod, fn, site, ctx)
                if status == "span":
                    status = f"span:{detail}"
                elif status == "blocking":
                    status = "BLOCKING"
                rows.append((mod.path, line, fn.qualname, site.kind,
                             status, site.producer))
    rows.sort()
    for path, line, sym, kind, status, producer in rows:
        print(f"{path}:{line}: [{sym}] {kind:<22} {status:<18} "
              f"<- {producer}")
    blocking = sum(1 for r in rows if r[4] == "BLOCKING")
    print(f"vlint --sync-inventory: {len(rows)} host-sync site(s), "
          f"{blocking} outside allowlisted spans")
    if budget is not None and len(rows) > budget:
        # the CI ratchet of the async-overlap burn-down (ROADMAP item 2):
        # the pipelined refactor shrank this inventory — a NEW sync site
        # (even span-excused) must justify itself by raising the budget
        # in ci/check.sh, not slide in silently
        print(f"vlint --sync-inventory: FAILED — {len(rows)} site(s) "
              f"exceed the --sync-budget of {budget}; remove the new "
              f"host sync or raise the budget with a written "
              f"justification (docs/static-analysis.md)")
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="vlint",
        description="contract-aware static analysis for volcano_tpu "
                    "(docs/static-analysis.md)")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the volcano_tpu package)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE} "
                             f"at the repo root when present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "(preserving existing justifications)")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="VTxxx", help="run only these rules")
    parser.add_argument("--rules", action="append", default=None,
                        metavar="VTxxx,VTyyy",
                        help="comma-separated rule selection "
                             "(combines with --rule)")
    parser.add_argument("--dataflow", action="store_true",
                        help="run only the dataflow-engine rules "
                             f"({', '.join(DATAFLOW_RULE_IDS)})")
    parser.add_argument("--diff", default=None, metavar="BASE",
                        help="restrict findings to functions whose bodies "
                             "changed vs this git ref (pure git diff "
                             "line ranges; full-tree runs stay the CI "
                             "gate)")
    parser.add_argument("--explain", default=None, metavar="VTxxx",
                        help="print the rule's contract and a minimal "
                             "trigger example, then exit")
    parser.add_argument("--sarif-out", default=None, metavar="FILE",
                        help="additionally write a SARIF 2.1.0 report to "
                             "FILE (the gating run can feed PR diff "
                             "annotation without a second analysis)")
    parser.add_argument("--sync-inventory", action="store_true",
                        help="print every VT010 host-sync site (excused "
                             "or not) with producer and span context — "
                             "the async-overlap worklist")
    parser.add_argument("--sync-budget", type=int, default=None,
                        metavar="N",
                        help="with --sync-inventory: exit 1 if the total "
                             "site count exceeds N (the CI ratchet that "
                             "keeps the inventory from growing)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.name}: {rule.contract}")
        return 0

    if args.explain:
        return _explain(args.explain)

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"vlint: no such path: {p}", file=sys.stderr)
            return 2

    if args.diff is not None and args.update_baseline:
        # a diff-restricted finding set would silently TRUNCATE the
        # baseline to the changed functions; the full-tree set is the
        # only valid input for a baseline rewrite. Checked BEFORE any
        # analysis runs — a usage error must not cost a full pass.
        print("vlint: --update-baseline cannot be combined with --diff "
              "(the baseline must be rewritten from the full-tree "
              "finding set)", file=sys.stderr)
        return 2

    if args.sync_inventory:
        return _sync_inventory(paths, budget=args.sync_budget)

    selected: List[str] = list(args.rule or [])
    for chunk in args.rules or []:
        selected.extend(r.strip() for r in chunk.split(",") if r.strip())
    if args.dataflow:
        selected.extend(DATAFLOW_RULE_IDS)

    rules = ALL_RULES
    if selected:
        rules = []
        for rid in dict.fromkeys(selected):          # dedupe, keep order
            rule = rule_by_id(rid)
            if rule is None:
                print(f"vlint: unknown rule {rid!r} (--list-rules)",
                      file=sys.stderr)
                return 2
            rules.append(rule)

    findings, invalid, ctx = analyze_paths(paths, rules=rules)

    dropped = 0
    if args.diff is not None:
        from .diff import (DiffError, changed_ranges, repo_root_for,
                           restrict_findings)
        try:
            ranges = changed_ranges(args.diff, cwd=repo_root_for(paths))
        except DiffError as exc:
            print(f"vlint: {exc}", file=sys.stderr)
            return 2
        findings, d1 = restrict_findings(findings, ctx, ranges)
        invalid, d2 = restrict_findings(invalid, ctx, ranges)
        dropped = d1 + d2

    baseline_path = None if args.no_baseline else (
        args.baseline or _find_baseline(paths))
    try:
        baseline = load_baseline(baseline_path)
    except BaselineError as exc:
        print(f"vlint: {exc}", file=sys.stderr)
        return 2

    live, grandfathered = split_baselined(findings, baseline)

    if args.update_baseline:
        target = baseline_path or os.path.join(os.getcwd(),
                                               DEFAULT_BASELINE)
        merged = live + grandfathered
        write_baseline(target, merged, justifications={
            key: entry["justification"]
            for key, entry in baseline.entries.items()
            if entry.get("justification")})
        print(f"vlint: wrote {len(merged)} entr"
              f"{'y' if len(merged) == 1 else 'ies'} to {target}; "
              f"replace any TODO justifications before committing")
        return 0

    if args.sarif_out:
        with open(args.sarif_out, "w", encoding="utf-8") as fh:
            fh.write(sarif_report(live, invalid, grandfathered))
            fh.write("\n")

    if args.format == "json":
        print(json_report(live, invalid, grandfathered, baseline))
    elif args.format == "sarif":
        print(sarif_report(live, invalid, grandfathered))
    else:
        print(text_report(live, invalid, grandfathered, baseline))
        if args.diff is not None:
            print(f"vlint: --diff {args.diff}: {dropped} finding(s) in "
                  f"unchanged functions not shown (full-tree pass "
                  f"remains the CI gate)")
    return exit_code(live, invalid)


if __name__ == "__main__":
    sys.exit(main())
