"""Deterministic fault-injection harness (docs/robustness.md).

Seeded chaos wrappers over the cache's side-effect executors
(cache/executors.py Binder/Evictor/StatusUpdater) plus an action-level
exception injector for the scheduler shell's per-action isolation
(scheduler.Scheduler.action_fault_hook). Everything is driven by one
``random.Random(seed)`` per wrapper, so a failing chaos test reproduces
exactly from its printed seed — no global RNG, no wall-clock coupling.

Typical rig::

    binder = ChaosBinder(FakeBinder(), failure_rate=0.2, seed=7)
    evictor = ChaosEvictor(FakeEvictor(), failure_rate=0.2, seed=7)
    cache = SchedulerCache(binder=binder, evictor=evictor)
    sched = Scheduler(cache, conf_text=...)
    sched.action_fault_hook = ActionFaultInjector(
        {"backfill": [2, 5]})          # raise on cycles 2 and 5
    for _ in range(20):
        sched.run_once()

The wrappers fail BEFORE invoking the inner executor (the failed attempt
has no side effect — the k8s API error model the resync queue assumes),
count attempts/failures per operation, and optionally sleep a fixed
latency on success to surface timing races.
"""

from __future__ import annotations

import random
import time
from typing import Dict, Iterable, List, Optional, Tuple

from .cache.executors import Binder, Evictor, StatusUpdater


class ChaosError(RuntimeError):
    """The injected failure type; carries the wrapper seed and attempt
    index so a log line alone is enough to reproduce."""

    def __init__(self, what: str, seed: int, attempt: int):
        super().__init__(f"chaos: injected {what} failure "
                         f"(seed={seed}, attempt={attempt})")
        self.what = what
        self.seed = seed
        self.attempt = attempt


class _ChaosWrapper:
    """Shared machinery: one seeded RNG, per-op attempt/failure counters."""

    def __init__(self, failure_rate: float = 0.2, latency: float = 0.0,
                 seed: int = 0):
        if not 0.0 <= failure_rate <= 1.0:
            raise ValueError(f"failure_rate {failure_rate} not in [0, 1]")
        self.failure_rate = failure_rate
        self.latency = latency
        self.seed = seed
        self._rng = random.Random(seed)
        self.attempts = 0
        self.failures = 0

    def _roll(self, what: str) -> None:
        """Raise ChaosError on a seeded coin flip; sleep the configured
        latency otherwise. Called before the inner executor so a failed
        attempt has no side effect."""
        self.attempts += 1
        if self._rng.random() < self.failure_rate:
            self.failures += 1
            raise ChaosError(what, self.seed, self.attempts)
        if self.latency:
            time.sleep(self.latency)


class ChaosBinder(_ChaosWrapper, Binder):
    def __init__(self, inner: Binder, failure_rate: float = 0.2,
                 latency: float = 0.0, seed: int = 0):
        _ChaosWrapper.__init__(self, failure_rate, latency, seed)
        self.inner = inner

    def bind(self, task, hostname: str) -> None:
        self._roll("bind")
        self.inner.bind(task, hostname)


class ChaosEvictor(_ChaosWrapper, Evictor):
    def __init__(self, inner: Evictor, failure_rate: float = 0.2,
                 latency: float = 0.0, seed: int = 0):
        _ChaosWrapper.__init__(self, failure_rate, latency, seed)
        self.inner = inner

    def evict(self, task, reason: str) -> None:
        self._roll("evict")
        self.inner.evict(task, reason)


class ChaosStatusUpdater(_ChaosWrapper, StatusUpdater):
    def __init__(self, inner: Optional[StatusUpdater] = None,
                 failure_rate: float = 0.2, latency: float = 0.0,
                 seed: int = 0):
        _ChaosWrapper.__init__(self, failure_rate, latency, seed)
        self.inner = inner or StatusUpdater()

    def update_pod_condition(self, task, condition: dict) -> None:
        self._roll("update_pod_condition")
        self.inner.update_pod_condition(task, condition)

    def update_pod_group(self, job) -> None:
        self._roll("update_pod_group")
        self.inner.update_pod_group(job)


class StoreFaultInjector:
    """Seeded per-verb fault plan for the API-server boundary — drives
    :class:`volcano_tpu.store_transport.FaultyStoreTransport`. Every
    store verb call rolls ONE seeded coin; a hit picks a fault kind by
    seeded weighted choice among the kinds legal for that verb:

    - ``transient``  — TransientStoreError (500/etcd-timeout analogue;
      the retrying transport absorbs it with backoff),
    - ``conflict``   — ConflictError on WRITE verbs (409; CAS loops
      re-read, non-CAS writers surface it like any error),
    - ``latency``    — a slow verb: ``sleep_fn(latency_s)`` then success
      (virtual seconds under the sim's clock — deterministic).

    Watch streams tear separately: ``roll_tear()`` is consulted per
    delivered watch event, and the sim additionally schedules whole-
    stream tears at seeded cycles. All RNG is one ``random.Random(seed)``
    per injector — a failing soak reproduces from its printed seed."""

    READ_VERBS = ("get", "list")
    WRITE_VERBS = ("create", "create_batch", "update", "update_status",
                   "delete", "bind_pod", "evict_pod")

    def __init__(self, failure_rate: float = 0.2, seed: int = 0,
                 conflict_share: float = 0.25, latency_share: float = 0.25,
                 latency_s: float = 0.05, tear_rate: float = 0.0,
                 sleep_fn=None):
        if not 0.0 <= failure_rate <= 1.0:
            raise ValueError(f"failure_rate {failure_rate} not in [0, 1]")
        self.failure_rate = failure_rate
        self.conflict_share = conflict_share
        self.latency_share = latency_share
        self.latency_s = latency_s
        self.tear_rate = tear_rate
        self.seed = seed
        self.sleep_fn = sleep_fn or time.sleep
        self._rng = random.Random(seed)
        self.attempts = 0
        self.injected: Dict[str, int] = {}     # kind -> count

    def _pick_kind(self, verb: str) -> str:
        r = self._rng.random()
        if verb not in self.READ_VERBS and r < self.conflict_share:
            return "conflict"
        if r < self.conflict_share + self.latency_share:
            return "latency"
        return "transient"

    def roll(self, verb: str) -> Optional[str]:
        """One verb attempt: returns the injected fault kind ("latency"
        is applied here — the sleep — and reported for counting), or
        None for a clean call."""
        self.attempts += 1
        if self._rng.random() >= self.failure_rate:
            return None
        kind = self._pick_kind(verb)
        self.injected[kind] = self.injected.get(kind, 0) + 1
        if kind == "latency":
            if self.latency_s:
                self.sleep_fn(self.latency_s)
        return kind

    def roll_tear(self) -> bool:
        """Per-delivered-watch-event tear roll (a torn stream stops
        receiving until its owner resumes it)."""
        if not self.tear_rate:
            return False
        if self._rng.random() >= self.tear_rate:
            return False
        self.injected["torn_watch"] = self.injected.get("torn_watch", 0) + 1
        return True


class AckFaultInjector:
    """Seeded fault plan for the cluster→scheduler FEEDBACK plane (the
    kubelet/status ack wire; docs/robustness.md feedback failure model).
    Every offered ack rolls ONE seeded coin; a hit picks a kind by
    seeded weighted choice:

    - ``delay``     — the ack arrives ``delay_s`` late (virtual seconds
      under the sim's clock — deterministic);
    - ``drop``      — the ack never arrives; only the in-flight
      watchdog's re-validation can settle the side effect;
    - ``duplicate`` — the ack arrives twice (the replay ``delay_s``
      later); the FeedbackChannel normalizer must make the second a
      no-op;
    - ``reorder``   — the ack is delivered AFTER the next ack offered
      (adjacent swap), the evict-ack/bind-ack inversion drill;
    - ``stale``     — the ack arrives, then is REPLAYED ``stale_delay_s``
      later — long enough that the placement it confirms is usually
      dead (evicted/completed); the replay must not resurrect it.

    One ``random.Random(seed)`` per injector — a failing soak reproduces
    from its printed seed. Counted in volcano_ack_faults_total{kind}."""

    KINDS = ("delay", "drop", "duplicate", "reorder", "stale")
    DEFAULT_SHARES = (("delay", 0.35), ("drop", 0.2), ("duplicate", 0.15),
                      ("reorder", 0.15), ("stale", 0.15))

    def __init__(self, failure_rate: float = 0.3, seed: int = 0,
                 delay_s: float = 2.5, stale_delay_s: float = 6.5,
                 shares=None):
        if not 0.0 <= failure_rate <= 1.0:
            raise ValueError(f"failure_rate {failure_rate} not in [0, 1]")
        self.failure_rate = failure_rate
        self.seed = seed
        self.delay_s = delay_s
        self.stale_delay_s = stale_delay_s
        self.shares = tuple(shares) if shares is not None \
            else self.DEFAULT_SHARES
        self._rng = random.Random(seed)
        self.attempts = 0
        self.injected: Dict[str, int] = {}     # kind -> count

    def roll(self, ack_kind: str) -> Optional[str]:
        """One offered ack: returns the injected fault kind, or None for
        a clean delivery."""
        self.attempts += 1
        if self._rng.random() >= self.failure_rate:
            return None
        total = sum(w for _, w in self.shares)
        r = self._rng.random() * total
        kind = self.shares[-1][0]
        for name, w in self.shares:
            if r < w:
                kind = name
                break
            r -= w
        self.injected[kind] = self.injected.get(kind, 0) + 1
        from . import metrics
        metrics.register_ack_fault(kind)
        return kind


class OverloadInjector:
    """Seeded arrival-burst generator for the admission-overload drills
    (docs/robustness.md overload failure model). Each ``tick()`` (one
    virtual cycle) rolls ONE seeded coin; a hit yields a burst of
    ``burst_range`` synthetic jobs on top of whatever the trace already
    delivers — the flash-crowd the backpressure budget must shed and
    the cycle budget must survive. ``job_spec(n_queues)`` draws one
    burst job's shape (queue index, priority, gang size, resources,
    duration) from the same seeded RNG, so a whole overload soak is a
    pure function of its seed and replays byte-identically.

    One ``random.Random(seed)`` per injector — a failing soak
    reproduces from its printed seed, like every other chaos harness
    here."""

    def __init__(self, burst_rate: float = 0.15,
                 burst_range: Tuple[int, int] = (8, 32), seed: int = 0,
                 priorities: Iterable[int] = (0, 0, 0, 5, 10),
                 cpu_choices: Iterable[int] = (500, 1000),
                 duration_range: Tuple[float, float] = (2.0, 6.0)):
        if not 0.0 <= burst_rate <= 1.0:
            raise ValueError(f"burst_rate {burst_rate} not in [0, 1]")
        self.burst_rate = burst_rate
        self.burst_range = tuple(burst_range)
        self.seed = seed
        self.priorities = tuple(priorities)
        self.cpu_choices = tuple(cpu_choices)
        self.duration_range = tuple(duration_range)
        self._rng = random.Random(seed)
        self.ticks = 0
        self.injected = 0
        self.bursts: List[Tuple[int, int]] = []   # (tick, size)

    def tick(self) -> int:
        """One cycle: 0 (no burst) or the seeded burst size."""
        self.ticks += 1
        if self._rng.random() >= self.burst_rate:
            return 0
        lo, hi = self.burst_range
        size = self._rng.randint(int(lo), int(hi))
        self.bursts.append((self.ticks, size))
        self.injected += size
        return size

    def job_spec(self, n_queues: int) -> Dict[str, object]:
        """One burst job's seeded shape; the caller names it and routes
        it through the admission front door like any client POST."""
        lo, hi = self.duration_range
        return {
            "queue_ix": self._rng.randrange(max(int(n_queues), 1)),
            "priority": self._rng.choice(self.priorities),
            "tasks": self._rng.choice((1, 1, 2)),
            "cpu_milli": self._rng.choice(self.cpu_choices),
            "duration": round(self._rng.uniform(lo, hi), 3),
        }


class DeviceFaultInjector:
    """Simulate XLA device errors (OOM / device-lost) at the allocate
    solve boundary — install as ``actions.allocate.DEVICE_FAULT_HOOK``.

    ``plan`` maps a fault kind ("oom" | "device_lost") to the 1-based
    SOLVE-ATTEMPT indices on which to raise (each hook call is one
    device solve attempt); with ``failure_rate`` set, every attempt
    instead rolls a seeded coin and picks a kind round-robin from
    ``plan``'s keys (pass {"oom": ()}). Raises
    ``device_health.DeviceFaultError`` — classified exactly like the
    real XlaRuntimeError, so the cool-down state machine, epoch bump and
    CPU degradation path are exercised end to end::

        from volcano_tpu.actions import allocate
        allocate.DEVICE_FAULT_HOOK = DeviceFaultInjector(
            {"oom": [2]})             # second solve attempt OOMs
    """

    def __init__(self, plan: Dict[str, Iterable[int]],
                 failure_rate: Optional[float] = None, seed: int = 0):
        self.plan = {kind: set(attempts) for kind, attempts in plan.items()}
        self.failure_rate = failure_rate
        self.seed = seed
        self._rng = random.Random(seed)
        self.attempt = 0
        self.injected: List[tuple] = []    # (attempt, kind)

    def __call__(self, engine: str) -> None:
        from .device_health import DeviceFaultError
        self.attempt += 1
        kind = None
        if self.failure_rate is not None:
            if self._rng.random() < self.failure_rate:
                kinds = sorted(self.plan) or ["oom"]
                kind = kinds[len(self.injected) % len(kinds)]
        else:
            for k, attempts in self.plan.items():
                if self.attempt in attempts:
                    kind = k
                    break
        if kind is None:
            return
        self.injected.append((self.attempt, kind))
        msg = ("RESOURCE_EXHAUSTED: Out of memory allocating device buffer"
               if kind == "oom" else
               "DEVICE_LOST: device lost (simulated)")
        raise DeviceFaultError(kind, f"chaos: {msg} "
                                     f"(seed={self.seed}, "
                                     f"attempt={self.attempt})")


class MeshFaultInjector:
    """Per-SHARD device faults for the unified sharded engine — install
    as ``actions.allocate.DEVICE_FAULT_HOOK`` (same socket as
    ``DeviceFaultInjector``, so the two are interchangeable per run).

    Where ``DeviceFaultInjector`` raises anonymous faults (the fleet
    cool-down path), this one ATTRIBUTES each fault to a live shard:
    the raised ``DeviceFaultError`` carries ``device=<id>`` picked
    seeded from ``allocate.CURRENT_MESH_DEVICES`` — the device-id tuple
    the current solve attempt actually runs over, refreshed per heal
    retry — so the per-device lattice quarantines exactly one chip and
    the mesh heals mid-cycle instead of degrading to CPU. Kinds:
    "oom", "device_lost", and "slow" (a slow-shard straggler,
    classified as a device fault by the ``DEADLINE_EXCEEDED`` marker).

    ``plan`` maps kind -> 1-based solve-attempt indices, or set
    ``failure_rate`` for a seeded coin per attempt (same contract as
    ``DeviceFaultInjector``). Probe dry-runs (hook calls named
    ``"<engine>:probe:<id>"``) are separate attempts and fault against
    the PROBED device when their index is in the plan — that is how a
    test keeps a chip quarantined across probe windows. Faults recorded
    in ``injected`` as ``(attempt, kind, device)``."""

    _MESSAGES = {
        "oom": "RESOURCE_EXHAUSTED: Out of memory allocating device buffer",
        "device_lost": "DEVICE_LOST: device lost (simulated)",
        "slow": "DEADLINE_EXCEEDED: collective timed out waiting on shard"
                " (simulated straggler)",
    }

    def __init__(self, plan: Dict[str, Iterable[int]],
                 failure_rate: Optional[float] = None, seed: int = 0):
        self.plan = {kind: set(attempts) for kind, attempts in plan.items()}
        self.failure_rate = failure_rate
        self.seed = seed
        self._rng = random.Random(seed)
        self.attempt = 0
        self.injected: List[tuple] = []    # (attempt, kind, device)

    def _pick_kind(self) -> Optional[str]:
        if self.failure_rate is not None:
            if self._rng.random() < self.failure_rate:
                kinds = sorted(self.plan) or ["device_lost"]
                return kinds[len(self.injected) % len(kinds)]
            return None
        for k, attempts in self.plan.items():
            if self.attempt in attempts:
                return k
        return None

    def __call__(self, engine: str) -> None:
        from .device_health import DeviceFaultError
        self.attempt += 1
        kind = self._pick_kind()
        if kind is None:
            return
        if ":probe:" in engine:
            device = int(engine.rsplit(":", 1)[1])
        else:
            from .actions.allocate import CURRENT_MESH_DEVICES
            if not CURRENT_MESH_DEVICES:
                return               # nothing live to attribute to
            device = CURRENT_MESH_DEVICES[
                self._rng.randrange(len(CURRENT_MESH_DEVICES))]
        self.injected.append((self.attempt, kind, device))
        raise DeviceFaultError(
            kind, f"chaos: {self._MESSAGES[kind]} on device {device} "
                  f"(seed={self.seed}, attempt={self.attempt})",
            device=device)


class SimKill(BaseException):
    """A simulated process death. Derives from BaseException ON PURPOSE:
    the cache's bind/evict funnels catch ``Exception`` to roll back and
    resync — a real crash does neither, so the kill must tunnel through
    every except-Exception layer, leaving optimistic cache state and the
    journal's unacked intent exactly as a SIGKILL would. The restart
    harness (sim/runner.SimRunner) catches it at the cycle boundary."""

    def __init__(self, where: str):
        super().__init__(f"simulated crash at {where}")
        self.where = where


class KillPointBinder(Binder):
    """Binder wrapper that crashes the process at a chosen bind within a
    chosen cycle window — BEFORE the inner executor runs (the side
    effect never reached the cluster) or AFTER it (the cluster has the
    bind; the cache/journal never learned). Arm with ``arm(n, before)``;
    fires once per arming. Wrap OUTERMOST (outside any ChaosBinder) so
    kill-after still records the inner executor's side effect first."""

    def __init__(self, inner: Binder):
        self.inner = inner
        self._armed: Optional[Tuple[int, bool]] = None
        self._count = 0
        self.kills: List[tuple] = []       # (bind_index, before)

    def arm(self, at_bind: int, before: bool) -> None:
        self._armed = (at_bind, before)
        self._count = 0

    def disarm(self) -> None:
        self._armed = None

    def bind(self, task, hostname: str) -> None:
        if self._armed is not None:
            at, before = self._armed
            self._count += 1
            if self._count >= at:
                if before:
                    self._armed = None
                    self.kills.append((self._count, True))
                    raise SimKill(f"bind #{self._count} (before execute)")
                self.inner.bind(task, hostname)
                self._armed = None
                self.kills.append((self._count, False))
                raise SimKill(f"bind #{self._count} (after execute)")
        self.inner.bind(task, hostname)


class KillPointEvictor(Evictor):
    """Evictor twin of KillPointBinder."""

    def __init__(self, inner: Evictor):
        self.inner = inner
        self._armed: Optional[Tuple[int, bool]] = None
        self._count = 0
        self.kills: List[tuple] = []

    def arm(self, at_evict: int, before: bool) -> None:
        self._armed = (at_evict, before)
        self._count = 0

    def disarm(self) -> None:
        self._armed = None

    def evict(self, task, reason: str) -> None:
        if self._armed is not None:
            at, before = self._armed
            self._count += 1
            if self._count >= at:
                if before:
                    self._armed = None
                    self.kills.append((self._count, True))
                    raise SimKill(f"evict #{self._count} (before execute)")
                self.inner.evict(task, reason)
                self._armed = None
                self.kills.append((self._count, False))
                raise SimKill(f"evict #{self._count} (after execute)")
        self.inner.evict(task, reason)


class LeaseLossInjector:
    """Revoke a replica's leadership MID-CYCLE at chosen (cycle, action)
    points — the HA demotion drill (docs/robustness.md): the leader must
    abandon its open session at the next action boundary instead of
    half-applying it, and its post-demotion writes must be fenced.

    This is the STANDALONE form for single-scheduler rigs and tests;
    the HA sim (`sim --ha N --lease-loss-cycles`) implements the same
    drill inside its per-replica action hook, where the revocation must
    track whichever replica currently leads.

    ``plan`` maps 1-based CYCLE indices to the 1-based ACTION ordinal
    before which the revocation lands (``{3: 2}`` = on cycle 3, revoke
    just before the second action runs). Install as (or compose into)
    ``Scheduler.action_fault_hook`` — it never raises; the scheduler's
    own demotion check does the rest. ``elector_fn`` returns the live
    elector (replicas swap electors across restarts)."""

    def __init__(self, elector_fn, plan: Dict[int, int]):
        self.elector_fn = elector_fn
        self.plan = dict(plan)
        self.cycle = 0
        self._seen_this_cycle: set = set()
        self.injected: List[tuple] = []    # (cycle, action_ordinal)

    def __call__(self, name: str, ssn) -> None:
        if name in self._seen_this_cycle:
            self._seen_this_cycle.clear()
        if not self._seen_this_cycle:
            self.cycle += 1
        self._seen_this_cycle.add(name)
        at = self.plan.get(self.cycle)
        if at is None or len(self._seen_this_cycle) != at:
            return
        elector = self.elector_fn()
        if elector is None or not elector.leading:
            return
        self.injected.append((self.cycle, at))
        elector.revoke()


class ClockSkewInjector:
    """Wrap a wall-clock ``time_fn`` with a steerable offset — the NTP
    step model for lease-clock skew: lease TIMESTAMPS (cross-process,
    wall-based) skew with the offset while the renew-deadline watchdog
    keeps reading the untouched monotonic clock, which is exactly the
    split the PR 6 fix established. Tests/sims set ``offset`` (or call
    ``step``) mid-run to model the NTP daemon slewing or stepping the
    clock."""

    def __init__(self, base_fn, offset: float = 0.0):
        self.base_fn = base_fn
        self.offset = offset

    def step(self, delta: float) -> None:
        self.offset += delta

    def __call__(self) -> float:
        return self.base_fn() + self.offset


class ActionFaultInjector:
    """Raise inside chosen actions on chosen cycles — the hook the
    scheduler shell calls before each action (Scheduler.action_fault_hook).

    ``plan`` maps action name -> iterable of 1-based CYCLE indices on
    which that action raises; the cycle counter increments each time the
    first configured action of the pipeline is seen again. With
    ``failure_rate`` set instead, every listed action fails on a seeded
    coin flip (plan values are ignored then; pass {"allocate": ()}).
    """

    def __init__(self, plan: Dict[str, Iterable[int]],
                 failure_rate: Optional[float] = None, seed: int = 0):
        self.plan = {name: set(cycles) for name, cycles in plan.items()}
        self.failure_rate = failure_rate
        self.seed = seed
        self._rng = random.Random(seed)
        self.cycle = 0
        self._seen_this_cycle: set = set()
        self.injected: List[tuple] = []    # (cycle, action)

    def __call__(self, name: str, ssn) -> None:
        # a repeated action name marks the next cycle (run_once walks the
        # pipeline in order, once per cycle)
        if name in self._seen_this_cycle:
            self._seen_this_cycle.clear()
        if not self._seen_this_cycle:
            self.cycle += 1
        self._seen_this_cycle.add(name)
        if name not in self.plan:
            return
        if self.failure_rate is not None:
            if self._rng.random() >= self.failure_rate:
                return
        elif self.cycle not in self.plan[name]:
            return
        self.injected.append((self.cycle, name))
        raise ChaosError(f"action:{name}", self.seed, self.cycle)
