"""Workload trace schema: the JSONL event log a simulation replays.

One event per line, ordered by non-decreasing virtual time ``t`` (seconds
from simulation start). Kinds and their payloads:

- ``queue_add``   {name, weight} — queue created (must precede arrivals
  into it; generators emit all queues at t=0).
- ``node_add``    {name, cpu_milli, mem, pods, gpus} — node joins.
- ``node_drain``  {name} — cordon: the node stops receiving placements
  (dropped from snapshots) but its running tasks run to completion.
- ``node_restore`` {name} — a drained node rejoins scheduling.
- ``node_fail``   {name} — the node dies: it leaves the cluster and every
  task on it is lost; lost tasks re-queue PENDING and their gang must
  re-admit (the job restarts, per gang semantics).
- ``job_arrival`` {name, queue, priority, tasks, min_available, cpu_milli,
  mem, gpus, duration} — a gang of ``tasks`` identical members arrives;
  it runs for ``duration`` virtual seconds once admitted
  (``min_available`` members placed), then completes.
- ``job_complete`` {name} — explicit completion (recorded traces); jobs
  without one complete ``duration`` seconds after admission.
- ``job_command`` {name, verb[, value]} — an elastic-gang lifecycle verb
  (``suspend`` / ``resume`` / ``scale``; ``scale`` carries the new
  desired member count in ``value``) submitted through the journaled
  Command funnel and consumed at the next cycle boundary.

Two payload keys are optional: ``job_arrival`` may carry ``desired``
(elastic gang: grow toward this member count; default = rigid gang) and
``node_add`` may carry ``zone`` (the node's ``volcano.sh/topology-zone``
label; default = unzoned).

The schema is flat and uniform-per-gang on purpose: it round-trips
losslessly through JSONL (`load_trace(write_trace(t)) == t`), and the
determinism tests treat the byte identity of a re-serialized trace as the
replay contract's precondition.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List

KINDS = ("queue_add", "node_add", "node_drain", "node_restore", "node_fail",
         "job_arrival", "job_complete", "job_command")

# required payload keys per kind (beyond t/kind); extra keys are rejected
# so schema drift fails at load time, not as a silently ignored field
_REQUIRED: Dict[str, tuple] = {
    "queue_add": ("name", "weight"),
    "node_add": ("name", "cpu_milli", "mem", "pods", "gpus"),
    "node_drain": ("name",),
    "node_restore": ("name",),
    "node_fail": ("name",),
    "job_arrival": ("name", "queue", "priority", "tasks", "min_available",
                    "cpu_milli", "mem", "gpus", "duration"),
    "job_complete": ("name",),
    "job_command": ("name", "verb"),
}

# optional payload keys per kind — absent in every pre-elastic trace, so
# old traces round-trip byte-identically
_OPTIONAL: Dict[str, tuple] = {
    "node_add": ("zone",),
    "job_arrival": ("desired",),
    "job_command": ("value",),
}


@dataclass(frozen=True)
class TraceEvent:
    """One trace line: virtual time, kind, and the kind's payload."""

    t: float
    kind: str
    data: Dict = field(default_factory=dict)

    def to_line(self) -> str:
        return json.dumps({"t": self.t, "kind": self.kind, **self.data},
                          sort_keys=True)

    @staticmethod
    def from_line(line: str) -> "TraceEvent":
        raw = json.loads(line)
        t = raw.pop("t")
        kind = raw.pop("kind")
        return TraceEvent(t=float(t), kind=kind, data=raw)

    def __post_init__(self):
        if self.kind not in _REQUIRED:
            raise ValueError(f"unknown trace event kind {self.kind!r} "
                             f"(known: {KINDS})")
        want = set(_REQUIRED[self.kind])
        got = set(self.data)
        extra = got - want - set(_OPTIONAL.get(self.kind, ()))
        if (want - got) or extra:
            raise ValueError(
                f"{self.kind} event payload mismatch at t={self.t}: "
                f"missing {sorted(want - got)}, unexpected {sorted(extra)}")
        if self.t < 0:
            raise ValueError(f"negative event time {self.t}")


def validate_trace(events: Iterable[TraceEvent]) -> List[TraceEvent]:
    """Check time ordering and referential integrity (arrivals name known
    queues, node/job lifecycle events name previously-added objects).
    Returns the events as a list."""
    out: List[TraceEvent] = []
    last_t = 0.0
    queues, nodes, jobs = set(), set(), set()
    for ev in events:
        if ev.t < last_t:
            raise ValueError(f"trace not time-ordered: {ev.kind} at {ev.t} "
                             f"after {last_t}")
        last_t = ev.t
        name = ev.data.get("name")
        if ev.kind == "queue_add":
            queues.add(name)
        elif ev.kind == "node_add":
            if name in nodes:
                raise ValueError(f"duplicate node_add {name!r}")
            nodes.add(name)
        elif ev.kind in ("node_drain", "node_restore", "node_fail"):
            if name not in nodes:
                raise ValueError(f"{ev.kind} for unknown node {name!r}")
            if ev.kind == "node_fail":
                nodes.discard(name)
        elif ev.kind == "job_arrival":
            if ev.data["queue"] not in queues:
                raise ValueError(f"job {name!r} arrives into unknown queue "
                                 f"{ev.data['queue']!r}")
            if name in jobs:
                raise ValueError(f"duplicate job_arrival {name!r}")
            if ev.data["tasks"] < 1 or not (
                    1 <= ev.data["min_available"] <= ev.data["tasks"]):
                raise ValueError(f"job {name!r}: bad gang shape "
                                 f"{ev.data['tasks']}/{ev.data['min_available']}")
            jobs.add(name)
        elif ev.kind == "job_complete":
            if name not in jobs:
                raise ValueError(f"job_complete for unknown job {name!r}")
        elif ev.kind == "job_command":
            if name not in jobs:
                raise ValueError(f"job_command for unknown job {name!r}")
            verb = ev.data["verb"]
            if verb not in ("suspend", "resume", "scale"):
                raise ValueError(f"job_command {name!r}: unknown verb "
                                 f"{verb!r}")
            if verb == "scale" and "value" not in ev.data:
                raise ValueError(f"job_command {name!r}: scale needs a "
                                 f"value")
        out.append(ev)
    return out


def write_trace(path: str, events: Iterable[TraceEvent]) -> int:
    """Write one JSONL line per event; returns the event count."""
    n = 0
    with open(path, "w") as f:
        for ev in events:
            f.write(ev.to_line() + "\n")
            n += 1
    return n


def load_trace(path: str) -> List[TraceEvent]:
    """Load and validate a JSONL trace file."""
    events = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                events.append(TraceEvent.from_line(line))
            except (KeyError, ValueError, json.JSONDecodeError) as exc:
                raise ValueError(f"{path}:{i}: bad trace line: {exc}") from exc
    return validate_trace(events)
