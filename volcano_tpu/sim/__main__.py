"""CLI: run a trace file or a named scenario through the simulator.

Examples::

    python -m volcano_tpu.sim --list
    python -m volcano_tpu.sim --scenario smoke
    python -m volcano_tpu.sim --scenario skew --seed 3 --out report.json
    python -m volcano_tpu.sim --scenario steady --write-trace steady.jsonl
    python -m volcano_tpu.sim --trace steady.jsonl --conf my.conf
"""

from __future__ import annotations

import argparse
import sys

from .report import deterministic_json, to_json
from .runner import SimRunner
from .trace import load_trace, write_trace
from .workload import SCENARIOS, make_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m volcano_tpu.sim",
        description="Trace-driven cluster simulation (docs/simulation.md)")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--scenario", help="named scenario (see --list)")
    src.add_argument("--trace", help="JSONL trace file to replay")
    src.add_argument("--list", action="store_true",
                     help="list named scenarios and exit")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--period", type=float, default=1.0,
                    help="virtual schedule period per cycle (default 1.0)")
    ap.add_argument("--conf", help="scheduler conf YAML file (default: the "
                                   "sim pipeline conf, runner.SIM_CONF)")
    ap.add_argument("--max-cycles", type=int, default=100000)
    ap.add_argument("--out", help="also write the report JSON to this file")
    ap.add_argument("--write-trace",
                    help="write the (generated) trace to this JSONL file")
    ap.add_argument("--deterministic", action="store_true",
                    help="print ONLY the decision plane as canonical JSON "
                         "(byte-comparable across runs — the CI "
                         "sim-determinism step diffs this)")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            print(f"{name:14s} {SCENARIOS[name]['description']}")
        return 0
    if args.scenario:
        trace = make_scenario(args.scenario, seed=args.seed)
    elif args.trace:
        trace = load_trace(args.trace)
    else:
        ap.error("one of --scenario/--trace/--list is required")
    if args.write_trace:
        write_trace(args.write_trace, trace)

    conf_text = None
    if args.conf:
        with open(args.conf) as f:
            conf_text = f.read()
    runner = SimRunner(trace, conf_text=conf_text, period=args.period,
                       seed=args.seed, max_cycles=args.max_cycles,
                       scenario=args.scenario)
    report = runner.run()
    text = deterministic_json(report) if args.deterministic \
        else to_json(report)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
