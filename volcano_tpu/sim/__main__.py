"""CLI: run a trace file or a named scenario through the simulator.

Examples::

    python -m volcano_tpu.sim --list
    python -m volcano_tpu.sim --scenario smoke
    python -m volcano_tpu.sim --scenario skew --seed 3 --out report.json
    python -m volcano_tpu.sim --scenario steady --write-trace steady.jsonl
    python -m volcano_tpu.sim --trace steady.jsonl --conf my.conf

Crash-recovery soak (docs/robustness.md; the CI chaos step)::

    python -m volcano_tpu.sim --scenario smoke --chaos-rate 0.2 \\
        --kill-cycles 3,7,12 --verify-restart-equivalence

HA soak (docs/robustness.md HA section; the CI ha-soak step) — three
replica schedulers, seeded LEADER kills + a mid-cycle lease loss,
verified against the single-scheduler oracle::

    python -m volcano_tpu.sim --scenario smoke --ha 3 \\
        --kill-cycles 2,5,9,13 --lease-loss-cycles 7 \\
        --verify-ha-equivalence
"""

from __future__ import annotations

import argparse
import sys

from .report import (deterministic_json, oracle_part, terminal_accounting,
                     to_json)
from .runner import SimRunner
from .trace import load_trace, write_trace
from .workload import SCENARIOS, make_scenario


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m volcano_tpu.sim",
        description="Trace-driven cluster simulation (docs/simulation.md)")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--scenario", help="named scenario (see --list)")
    src.add_argument("--trace", help="JSONL trace file to replay")
    src.add_argument("--list", action="store_true",
                     help="list named scenarios and exit")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--period", type=float, default=1.0,
                    help="virtual schedule period per cycle (default 1.0)")
    ap.add_argument("--conf", help="scheduler conf YAML file (default: the "
                                   "sim pipeline conf, runner.SIM_CONF)")
    ap.add_argument("--max-cycles", type=int, default=100000)
    ap.add_argument("--out", help="also write the report JSON to this file")
    ap.add_argument("--write-trace",
                    help="write the (generated) trace to this JSONL file")
    ap.add_argument("--deterministic", action="store_true",
                    help="print ONLY the decision plane as canonical JSON "
                         "(byte-comparable across runs — the CI "
                         "sim-determinism step diffs this)")
    ap.add_argument("--trace-out",
                    help="record the flight recorder for the whole run and "
                         "write the merged Chrome trace-event JSON "
                         "(perfetto-loadable) to this file; with "
                         "--deterministic the recorder uses its logical "
                         "clock, so the artifact is byte-reproducible "
                         "(docs/observability.md)")
    ap.add_argument("--chaos-rate", type=float, default=0.0,
                    help="seeded bind/evict failure rate (volcano_tpu."
                         "chaos wrappers; 0 = off)")
    ap.add_argument("--chaos-seed", type=int, default=None,
                    help="chaos RNG seed (default: --seed)")
    ap.add_argument("--kill-cycles", default="",
                    help="comma-separated virtual cycles on which to "
                         "crash+restart the scheduler mid-trace "
                         "(intent journal + startup reconciliation)")
    ap.add_argument("--kill-seed", type=int, default=None,
                    help="kill-point RNG seed (default: --seed)")
    ap.add_argument("--verify-restart-equivalence", action="store_true",
                    help="also run the SAME trace unkilled and assert the "
                         "killed run converged to the same terminal "
                         "decision-plane accounting with zero "
                         "double-binds (exit 1 otherwise)")
    ap.add_argument("--ha", type=int, default=1, metavar="N",
                    help="run N replica schedulers over one virtual "
                         "cluster (lease-based leadership + fencing "
                         "epochs + warm journal-tail standbys; "
                         "docs/robustness.md). --kill-cycles then kills "
                         "the LEADER at seeded adversarial points")
    ap.add_argument("--federated", type=int, default=0, metavar="N",
                    help="run N PARTITION schedulers over one virtual "
                         "cluster (disjoint queue subsets + node shards, "
                         "per-partition fenced leaders, cross-partition "
                         "reserve/transfer through the shared journal; "
                         "docs/federation.md). --kill-cycles then kills "
                         "a partition's leader at seeded adversarial "
                         "points")
    ap.add_argument("--verify-federated-equivalence", action="store_true",
                    help="also run the SAME trace single-scheduler and "
                         "assert equivalence: byte-identical aggregate "
                         "decision plane when the federated run is "
                         "non-contended (no kills), terminal-accounting "
                         "equivalence + zero cross-partition double-binds "
                         "otherwise (exit 1 on mismatch)")
    ap.add_argument("--lease-loss-cycles", default="",
                    help="comma-separated virtual cycles on which the "
                         "leader LOSES ITS LEASE mid-cycle (no process "
                         "death): it must abandon the open session, "
                         "demote to fenced, and a standby takes over")
    ap.add_argument("--verify-ha-equivalence", action="store_true",
                    help="also run the SAME trace single-replica and "
                         "assert equivalence: byte-identical decision "
                         "plane when the HA run is non-contended (no "
                         "kills/lease losses), terminal-accounting "
                         "equivalence + zero double-binds otherwise "
                         "(exit 1 on mismatch)")
    ap.add_argument("--store-wired", action="store_true",
                    help="cluster truth lives in a real ObjectStore "
                         "behind the hostile transport "
                         "(store_transport.py): informer-fed caches "
                         "with resumable watches, every scheduler "
                         "write through the retry funnel "
                         "(docs/simulation.md). Composes with "
                         "--federated N (store-backed PartitionState "
                         "CR)")
    ap.add_argument("--store-chaos", action="store_true",
                    help="the store-chaos soak preset: --store-wired "
                         "with 20%% seeded verb faults and 2 torn "
                         "watch streams (docs/robustness.md store "
                         "failure model); individual --store-* flags "
                         "override")
    ap.add_argument("--store-fault-rate", type=float, default=None,
                    help="seeded per-verb store fault rate (latency/"
                         "transient/409; implies --store-wired)")
    ap.add_argument("--store-fault-seed", type=int, default=None,
                    help="store fault RNG seed (default: --seed)")
    ap.add_argument("--torn-watches", type=int, default=None,
                    help="tear N watch streams at seeded cycles; the "
                         "resumable informers must recover by backlog "
                         "replay or 410-relist (implies --store-wired)")
    ap.add_argument("--verify-store-equivalence", action="store_true",
                    help="also run the SAME trace store-wired with "
                         "ZERO faults/tears/kills and assert the "
                         "chaotic run converged to the same terminal "
                         "accounting with zero double-binds (exit 1 "
                         "otherwise)")
    ap.add_argument("--ack-chaos", action="store_true",
                    help="the feedback-plane soak preset: seeded "
                         "kubelet/status ack faults at rate 0.3 "
                         "(delay/drop/duplicate/reorder/stale; "
                         "docs/robustness.md feedback failure model). "
                         "Direct modes fault the ack wire; with "
                         "--store-wired the watch-path RUNNING acks "
                         "are faulted instead")
    ap.add_argument("--ack-fault-rate", type=float, default=None,
                    help="seeded per-ack fault rate (overrides the "
                         "--ack-chaos preset)")
    ap.add_argument("--ack-fault-seed", type=int, default=None,
                    help="ack fault RNG seed (default: --seed)")
    ap.add_argument("--verify-ack-equivalence", action="store_true",
                    help="also run the SAME trace with a clean feedback "
                         "plane (no ack faults, no kills) and assert "
                         "the chaotic run converged to the same "
                         "terminal accounting with zero double-binds "
                         "and zero stuck in-flight entries (exit 1 "
                         "otherwise)")
    ap.add_argument("--lease-fault-rate", type=float, default=None,
                    help="seeded store-fault rate on the HA lease CAS "
                         "path (acquire/renew ride the retrying "
                         "transport; --ha/--federated only)")
    ap.add_argument("--lease-fault-seed", type=int, default=None,
                    help="lease fault RNG seed (default: --seed)")
    ap.add_argument("--overload-chaos", action="store_true",
                    help="the overload soak preset (docs/robustness.md "
                         "overload failure model): cycle deadline "
                         "budget 0.5 periods with the deterministic "
                         "per-pending-task cost model, bounded "
                         "admission (depth 48/queue) with "
                         "priority-aware shedding + retry-after "
                         "re-offers, seeded OverloadInjector arrival "
                         "bursts, and (with --federated) the "
                         "load-driven queue rebalancer; individual "
                         "--cycle-budget/--admission-depth/"
                         "--burst-rate/--rebalance flags override")
    ap.add_argument("--cycle-budget", type=float, default=None,
                    help="per-cycle deadline budget in virtual seconds "
                         "(0 = unbounded); actions defer past it with "
                         "carry-over ordering")
    ap.add_argument("--admission-depth", type=int, default=None,
                    help="per-queue accepted-work task cap at the "
                         "admission front door (0 = unbounded)")
    ap.add_argument("--burst-rate", type=float, default=None,
                    help="seeded OverloadInjector burst probability "
                         "per cycle (0 = off)")
    ap.add_argument("--rebalance", action="store_true",
                    help="enable the load-driven partition rebalancer "
                         "(requires --federated)")
    ap.add_argument("--elastic", action="store_true",
                    help="enable elastic partition membership: chronic "
                         "cycle-budget exhaustion SPLITS a partition "
                         "(journaled partition_spawn, queues transferred "
                         "through the reserve funnel), chronic idleness "
                         "MERGES it back (drain + partition_retire); "
                         "requires --federated N (N may be 1 — the "
                         "1->N->1 diurnal case; docs/federation.md)")
    ap.add_argument("--verify-elastic-equivalence", action="store_true",
                    help="assert the elastic contract: at least one "
                         "split AND one merge fired, membership "
                         "returned to the initial partition count, "
                         "per-queue depth stayed bounded, every "
                         "admitted gang completed with zero "
                         "double-binds, byte-deterministic x2 "
                         "(exit 1 otherwise)")
    ap.add_argument("--verify-overload-equivalence", action="store_true",
                    help="assert the overload contract: bounded "
                         "per-queue pending depth, max cycle spend "
                         "within 2x the budget, every admitted gang "
                         "completes (incl. retried shed arrivals), "
                         "zero double-binds, byte-deterministic x2; "
                         "with --federated --rebalance also that queue "
                         "ownership converged without operator "
                         "move_queue calls (exit 1 otherwise)")
    ap.add_argument("--pipelined", action="store_true",
                    help="run the pipelined scheduler shell "
                         "(speculative solve overlapped with host "
                         "commit; docs/performance.md). Single-"
                         "scheduler only")
    ap.add_argument("--fast-admit", action="store_true",
                    help="enable the event-driven fast-admit path: "
                         "trivially-fitting gangs bind between full "
                         "cycles through the journaled funnel")
    ap.add_argument("--elastic-gangs", action="store_true",
                    help="enable elastic GANG membership (distinct from "
                         "--elastic partition membership): gangs with a "
                         "desired count admit at min, the grow-shrink "
                         "stage expands them toward desired as capacity "
                         "frees and shrinks them first under pressure, "
                         "and suspend/resume/scale verbs ride the "
                         "journaled Command funnel "
                         "(docs/design/elastic-gangs.md). Direct "
                         "single-scheduler topology only")
    ap.add_argument("--topology-weight", type=float, default=10.0,
                    metavar="W",
                    help="zone-compactness weight for --elastic-gangs "
                         "(the allocate anchor term + the plugin's "
                         "node_order bonus); 0 = topology-unaware "
                         "baseline (default 10.0)")
    ap.add_argument("--verify-elastic-gang-equivalence",
                    action="store_true",
                    help="assert the elastic-gang contract: gangs "
                         "flexed (grows AND shrinks fired), zero "
                         "below-min evictions outside full-gang "
                         "decisions, zero rejected commands, every "
                         "arrived gang completed with zero "
                         "double-binds, byte-deterministic x2 "
                         "(exit 1 otherwise)")
    ap.add_argument("--sharded", action="store_true",
                    help="run allocate through the unified shard_map "
                         "engine (tpu-sharded: nodes axis sharded over "
                         "the device mesh, jobs replicated; "
                         "ops/unified.py). Single-scheduler only")
    ap.add_argument("--sharded-devices", type=int, default=0, metavar="N",
                    help="cap the sharded mesh to the first N devices "
                         "(0 = full mesh). N=1 is the single-device "
                         "oracle the equivalence verify diffs against")
    ap.add_argument("--verify-sharded-equivalence", action="store_true",
                    help="also run the SAME trace with sharded-devices=1 "
                         "(the single-device oracle — the unified "
                         "solver's decisions are mesh-size invariant by "
                         "construction) and assert the full-mesh decision "
                         "plane is BYTE-IDENTICAL (exit 1 on mismatch); "
                         "requires --sharded")
    ap.add_argument("--mesh-chaos", action="store_true",
                    help="the mesh fault soak preset (docs/robustness.md "
                         "mesh failure model): seeded per-shard "
                         "device-lost/OOM/slow-shard faults at rate 0.2 "
                         "(chaos.MeshFaultInjector), each attributed to "
                         "a live shard so the per-device lattice "
                         "quarantines exactly that chip, the mesh heals "
                         "mid-cycle over the survivors, and expired "
                         "quarantines are probed + readmitted on the "
                         "virtual clock. Implies --sharded")
    ap.add_argument("--mesh-fault-rate", type=float, default=None,
                    help="seeded per-solve-attempt mesh fault rate "
                         "(overrides the --mesh-chaos preset; implies "
                         "--mesh-chaos)")
    ap.add_argument("--mesh-fault-seed", type=int, default=None,
                    help="mesh fault RNG seed (default: --seed)")
    ap.add_argument("--verify-mesh-equivalence", action="store_true",
                    help="also run the SAME trace FAULT-FREE at "
                         "sharded-devices=1 (the healthy single-device "
                         "oracle) and assert the mesh-chaos decision "
                         "plane is byte-identical (mesh section "
                         "stripped), zero double-binds, faults actually "
                         "injected, a quarantined device readmitted, and "
                         "the CPU-placer rung never reached (exit 1 "
                         "otherwise); implies --mesh-chaos")
    ap.add_argument("--lifecycle", action="store_true",
                    help="derive the cluster-causal latency/SLO report "
                         "sections from the per-job lifecycle timelines "
                         "(obs/lifecycle.py): per-class ttfb/admission/"
                         "ack/jct percentiles plus the SLO burn-rate "
                         "evaluation; off by default so fault-free "
                         "decision planes stay byte-identical")
    ap.add_argument("--verify-pipelined-equivalence", action="store_true",
                    help="also run the SERIAL single-scheduler oracle "
                         "and assert equivalence: byte-identical "
                         "decision plane when the pipelined run never "
                         "conflicted (and fast-admit is off), terminal-"
                         "accounting equivalence + zero double-binds "
                         "otherwise (exit 1 on mismatch)")
    args = ap.parse_args(argv)

    # the mesh-chaos preset (docs/robustness.md mesh failure model):
    # resolved BEFORE the conf is pinned because it implies the sharded
    # engine — mesh faults are attributed per shard, and only the
    # unified sharded solver has shards to attribute to
    mesh_fault_rate = args.mesh_fault_rate
    mesh_chaos = bool(args.mesh_chaos or args.verify_mesh_equivalence
                      or mesh_fault_rate is not None)
    if mesh_chaos:
        if mesh_fault_rate is None:
            mesh_fault_rate = 0.2
        args.sharded = True
    mesh_fault_rate = mesh_fault_rate or 0.0

    if args.list:
        for name in sorted(SCENARIOS):
            print(f"{name:14s} {SCENARIOS[name]['description']}")
        return 0
    if args.scenario:
        trace = make_scenario(args.scenario, seed=args.seed)
    elif args.trace:
        trace = load_trace(args.trace)
    else:
        ap.error("one of --scenario/--trace/--list is required")
    if args.write_trace:
        write_trace(args.write_trace, trace)

    conf_text = None
    if args.conf:
        with open(args.conf) as f:
            conf_text = f.read()
    elif args.sharded:
        # pin the sharded conf explicitly: allocate on the unified
        # shard_map engine, mesh capped by --sharded-devices — the
        # equivalence oracle below swaps ONLY the device cap to 1
        from .runner import sharded_sim_conf
        conf_text = sharded_sim_conf(args.sharded_devices)
    elif args.pipelined or args.fast_admit:
        # pin the pipelined conf EXPLICITLY so the serial oracle of
        # --verify-pipelined-equivalence schedules with the identical
        # action pipeline/engine — the diff isolates the pipeline itself
        from .runner import PIPELINED_SIM_CONF
        conf_text = PIPELINED_SIM_CONF

    chaos_seed = args.seed if args.chaos_seed is None else args.chaos_seed
    kill_seed = args.seed if args.kill_seed is None else args.kill_seed
    kill_cycles = [int(c) for c in args.kill_cycles.split(",") if c.strip()]
    lease_loss = [int(c) for c in args.lease_loss_cycles.split(",")
                  if c.strip()]
    # the store-chaos preset (docs/robustness.md store failure model):
    # 20% verb faults + 2 torn watch streams over the store-wired world
    store_fault_rate = args.store_fault_rate
    torn_watches = args.torn_watches
    if args.store_chaos:
        if store_fault_rate is None:
            store_fault_rate = 0.2
        if torn_watches is None:
            torn_watches = 2
    # asking for the store-equivalence verdict implies the store-wired
    # world — otherwise the "baseline" would be a second identical
    # direct-mode run and the OK verdict vacuous
    store_wired = (args.store_wired or args.store_chaos
                   or args.verify_store_equivalence
                   or store_fault_rate is not None
                   or torn_watches is not None)
    store_fault_rate = store_fault_rate or 0.0
    torn_watches = torn_watches or 0
    # the feedback-plane preset (docs/robustness.md feedback failure
    # model): 30% seeded ack faults over the chosen topology
    ack_fault_rate = args.ack_fault_rate
    if args.ack_chaos and ack_fault_rate is None:
        ack_fault_rate = 0.3
    ack_fault_rate = ack_fault_rate or 0.0
    lease_fault_rate = args.lease_fault_rate or 0.0
    # the overload preset (docs/robustness.md overload failure model):
    # budget + bounded admission + seeded bursts (+ rebalancer when
    # federated); explicit flags override the preset values
    cycle_budget = args.cycle_budget
    admission_depth = args.admission_depth
    burst_rate = args.burst_rate
    rebalance = args.rebalance
    if args.overload_chaos:
        if cycle_budget is None:
            cycle_budget = 0.5 * args.period
        if admission_depth is None:
            admission_depth = 48
        if burst_rate is None:
            burst_rate = 0.2
        if args.federated:
            rebalance = True
    cycle_budget = cycle_budget or 0.0
    admission_depth = admission_depth or 0
    burst_rate = burst_rate or 0.0
    # the deterministic cost model prices one pending task per action
    # at 2ms of budget (scaled by the period like the budget itself):
    # with the preset's 48-task/queue admission cap the worst single
    # action charges ~0.38 periods < the 0.5 budget (one action may
    # overshoot but can never double the spend), while a saturated
    # 4-queue backlog walked by a 5-action pipeline charges ~1.5 —
    # exhaustion and deferral genuinely fire in the overload soaks
    budget_cost = 0.002 * args.period if cycle_budget else 0.0
    if rebalance and not args.federated:
        ap.error("--rebalance requires --federated N")
    if args.elastic and not args.federated:
        ap.error("--elastic requires --federated N (N may be 1)")
    if args.verify_elastic_equivalence and not args.elastic:
        ap.error("--verify-elastic-equivalence requires --elastic")
    if args.elastic_gangs and (args.federated or args.ha > 1 or store_wired
                               or args.pipelined or args.fast_admit):
        ap.error("--elastic-gangs is a direct single-scheduler mode "
                 "(not --federated / --ha / --store-wired / --pipelined "
                 "/ --fast-admit)")
    if args.verify_elastic_gang_equivalence and not args.elastic_gangs:
        ap.error("--verify-elastic-gang-equivalence requires "
                 "--elastic-gangs")
    if args.sharded and (args.federated or args.ha > 1 or args.pipelined
                         or args.elastic_gangs):
        ap.error("--sharded is a direct single-scheduler mode (not "
                 "--federated / --ha / --pipelined / --elastic-gangs)")
    if args.verify_sharded_equivalence and not args.sharded:
        ap.error("--verify-sharded-equivalence requires --sharded")
    if args.verify_ack_equivalence and not ack_fault_rate:
        # without faults the report has no feedback section and every
        # stuck-state assertion would pass vacuously
        ap.error("--verify-ack-equivalence requires ack faults "
                 "(--ack-chaos, or --ack-fault-rate > 0)")

    def wraps():
        if not args.chaos_rate:
            return None, None
        from ..chaos import ChaosBinder, ChaosEvictor
        return (lambda b: ChaosBinder(b, failure_rate=args.chaos_rate,
                                      seed=chaos_seed),
                lambda e: ChaosEvictor(e, failure_rate=args.chaos_rate,
                                       seed=chaos_seed))

    def run(kills, replicas=None, losses=None, federated=None,
            pipelined=None, fast_admit=None, fault_rate=None, torn=None,
            ack_rate=None, lease_rate=None, conf=None, mesh_rate=None):
        mesh_r = mesh_fault_rate if mesh_rate is None else mesh_rate
        bw, ew = wraps()
        runner = SimRunner(trace,
                           conf_text=conf_text if conf is None else conf,
                           period=args.period,
                           cycle_budget_s=cycle_budget,
                           budget_cost_per_task=budget_cost,
                           admission_depth=admission_depth,
                           overload_burst_rate=burst_rate,
                           rebalance=rebalance
                           and bool(args.federated
                                    if federated is None else federated),
                           elastic=args.elastic
                           and bool(args.federated
                                    if federated is None else federated),
                           seed=args.seed, max_cycles=args.max_cycles,
                           scenario=args.scenario, binder_wrap=bw,
                           evictor_wrap=ew, kill_cycles=kills,
                           kill_seed=kill_seed,
                           ha_replicas=args.ha if replicas is None
                           else replicas,
                           lease_loss_cycles=lease_loss if losses is None
                           else losses,
                           federated_partitions=args.federated
                           if federated is None else federated,
                           pipelined=args.pipelined if pipelined is None
                           else pipelined,
                           fast_admit=args.fast_admit if fast_admit is None
                           else fast_admit,
                           store_wired=store_wired,
                           store_fault_rate=store_fault_rate
                           if fault_rate is None else fault_rate,
                           store_fault_seed=args.store_fault_seed,
                           torn_watches=torn_watches if torn is None
                           else torn,
                           ack_fault_rate=ack_fault_rate
                           if ack_rate is None else ack_rate,
                           ack_fault_seed=args.ack_fault_seed,
                           lease_fault_rate=lease_fault_rate
                           if lease_rate is None else lease_rate,
                           lease_fault_seed=args.lease_fault_seed,
                           elastic_gangs=args.elastic_gangs,
                           topology_weight=args.topology_weight,
                           mesh_chaos=mesh_chaos and mesh_r > 0,
                           mesh_fault_rate=mesh_r,
                           mesh_fault_seed=args.mesh_fault_seed,
                           lifecycle=args.lifecycle)
        return runner.run()

    if args.trace_out:
        from ..obs import TRACE
        # unbounded ring for the run: --trace-out merges EVERY cycle into
        # one artifact instead of keeping only the live tail
        TRACE.configure(max_cycles=0, logical=args.deterministic)
        TRACE.enable()
    report = run(kill_cycles)
    if args.trace_out:
        TRACE.disable()
        TRACE.dump(args.trace_out)
        print(f"trace: {TRACE.cycles_recorded()} cycles -> "
              f"{args.trace_out}", file=sys.stderr)
    text = deterministic_json(report) if args.deterministic \
        else to_json(report)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.verify_restart_equivalence:
        baseline = run([])
        got = terminal_accounting(report)
        want = terminal_accounting(baseline)
        problems = []
        if got != want:
            problems.append(f"terminal accounting diverged: "
                            f"killed={got} unkilled={want}")
        if got.get("double_binds"):
            problems.append(f"double-binds in killed run: "
                            f"{got['double_binds']}")
        if got.get("unfinished"):
            problems.append(f"killed run left {got['unfinished']} jobs "
                            f"unfinished")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("killed run did not complete every arrived job")
        if problems:
            for p in problems:
                print(f"restart-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        print(f"restart-equivalence OK: {report['restarts']} restarts, "
              f"journal={report['journal_replayed']}, "
              f"accounting={got}", file=sys.stderr)
    if args.verify_store_equivalence:
        baseline = run([], fault_rate=0.0, torn=0, losses=[])
        got = terminal_accounting(report)
        want = terminal_accounting(baseline)
        problems = []
        if got != want:
            problems.append(f"terminal accounting diverged: "
                            f"chaotic={got} clean={want}")
        if got.get("double_binds"):
            problems.append(f"double-binds under store chaos: "
                            f"{got['double_binds']}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("store-chaos run did not complete every "
                            "arrived job")
        if problems:
            for p in problems:
                print(f"store-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        st = report.get("store", {})
        print(f"store-equivalence OK: faults={st.get('faults', {})}, "
              f"retry_funnel={st.get('retry_funnel', {})}, "
              f"torn={st.get('torn_watch_events', 0)}, "
              f"resumes={st.get('watch_resumes', 0)}, "
              f"relists={st.get('watch_relists', 0)}, "
              f"restarts={report.get('restarts', 0)}, "
              f"accounting={got}", file=sys.stderr)
    if args.verify_ack_equivalence:
        baseline = run([], losses=[], ack_rate=0.0, lease_rate=0.0)
        got = terminal_accounting(report)
        want = terminal_accounting(baseline)
        fb = report.get("feedback", {})
        problems = []
        if got != want:
            problems.append(f"terminal accounting diverged: "
                            f"ack-chaotic={got} clean={want}")
        if got.get("double_binds"):
            problems.append(f"double-binds under ack chaos: "
                            f"{got['double_binds']}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("ack-chaos run did not complete every "
                            "arrived job")
        if fb.get("inflight_open") or fb.get("wire_pending"):
            problems.append(
                f"stuck feedback state at run end: "
                f"inflight_open={fb.get('inflight_open')} "
                f"wire_pending={fb.get('wire_pending')}")
        if problems:
            for p in problems:
                print(f"ack-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        print(f"ack-equivalence OK: faults={fb.get('faults', {})}, "
              f"acks={fb.get('acks', {})}, "
              f"watchdog_fired={fb.get('watchdog_fired', 0)}, "
              f"restarts={report.get('restarts', 0)}, "
              f"accounting={got}", file=sys.stderr)
    if args.verify_overload_equivalence:
        ov = report.get("overload")
        problems = []
        if ov is None:
            problems.append("no overload section in the report — "
                            "enable --overload-chaos (or individual "
                            "overload flags)")
            ov = {}
        # byte-determinism x2: the overload machinery (cost model,
        # shed/retry stream, bursts, rebalancer) is seeded + virtual-
        # clock priced, so an identical re-run must reproduce the
        # decision plane byte-for-byte
        rerun = run(kill_cycles)
        if deterministic_json(report) != deterministic_json(rerun):
            problems.append("overload run not byte-deterministic x2")
        budget = ov.get("cycle_budget", {})
        if budget.get("budget_s"):
            if budget.get("max_cycle_spend_s", 0.0) \
                    > 2.0 * budget["budget_s"]:
                problems.append(
                    f"cycle spend exceeded 2x the budget: "
                    f"{budget['max_cycle_spend_s']} vs "
                    f"{budget['budget_s']}")
        adm = ov.get("admission", {})
        if adm:
            over = {q: d for q, d in adm.get("high_water", {}).items()
                    if d > adm["max_queue_depth"]}
            if over:
                problems.append(f"admission depth bound violated: "
                                f"{over} > {adm['max_queue_depth']}")
        if ov.get("retries_pending"):
            problems.append(f"{ov['retries_pending']} shed arrivals "
                            f"never re-admitted")
        if report["jobs"]["completed"] != report["jobs"]["arrived"] \
                or report["jobs"]["unfinished"]:
            problems.append("not every admitted gang completed: "
                            f"{report['jobs']}")
        if report.get("double_binds"):
            problems.append(f"double-binds under overload: "
                            f"{report['double_binds']}")
        reb = report.get("federation", {}).get("rebalance")
        if reb is not None and reb.get("enabled"):
            # a balanced world legitimately never moves (hysteresis
            # abstains) — the hotspot scenarios assert moves>0 in CI;
            # here the contract is CONVERGENCE: whatever moved must
            # have settled well before the run ended
            if reb.get("move_count") and reb["last_move_t"] \
                    > report["virtual_time_s"] - 10 * args.period:
                problems.append(
                    f"rebalancer still moving at run end (last move "
                    f"t={reb['last_move_t']}): ownership did not "
                    f"converge")
        if problems:
            for p in problems:
                print(f"overload-equivalence FAILED: {p}",
                      file=sys.stderr)
            return 1
        print(f"overload-equivalence OK: budget={budget}, "
              f"shed={ov.get('shed', {})}, "
              f"readmits={ov.get('readmit_attempts', 0)}, "
              f"bursts={ov.get('burst_jobs', 0)}, "
              f"rebalance_moves="
              f"{(reb or {}).get('move_count', 0)}, "
              f"restarts={report.get('restarts', 0)}, "
              f"accounting={terminal_accounting(report)}",
              file=sys.stderr)
    if args.verify_elastic_equivalence:
        el = report.get("federation", {}).get("elastic") or {}
        problems = []
        if not el.get("enabled"):
            problems.append("no elastic section in the report — the "
                            "controller never attached")
        if not el.get("splits"):
            problems.append("no partition split fired: the scenario "
                            "never sustained cycle-budget exhaustion "
                            "long enough (tune the flash crowd or the "
                            "budget preset)")
        if not el.get("merges"):
            problems.append("no partition merge fired: spawned "
                            "partitions never drained back")
        if el.get("partitions_final") != el.get("partitions_initial"):
            problems.append(
                f"membership did not return to the initial count: "
                f"final={el.get('partitions_final')} "
                f"initial={el.get('partitions_initial')}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"] \
                or report["jobs"]["unfinished"]:
            problems.append("not every admitted gang completed across "
                            f"membership changes: {report['jobs']}")
        if report.get("double_binds"):
            problems.append(f"double-binds across membership changes: "
                            f"{report['double_binds']}")
        adm = report.get("overload", {}).get("admission", {})
        if adm and adm.get("max_queue_depth"):
            over = {q: d for q, d in adm.get("high_water", {}).items()
                    if d > adm["max_queue_depth"]}
            if over:
                problems.append(f"per-queue depth bound violated across "
                                f"split/merge: {over} > "
                                f"{adm['max_queue_depth']}")
        # byte-determinism x2: split/merge triggers are virtual-clock
        # hysteresis over seeded load, so an identical re-run must
        # reproduce the decision plane byte-for-byte
        rerun = run(kill_cycles)
        if deterministic_json(report) != deterministic_json(rerun):
            problems.append("elastic run not byte-deterministic x2")
        if problems:
            for p in problems:
                print(f"elastic-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        print(f"elastic-equivalence OK: splits={el.get('splits')}, "
              f"merges={el.get('merges')}, "
              f"peak={el.get('partitions_peak')}, "
              f"final={el.get('partitions_final')}, "
              f"max_queue_depth={el.get('max_queue_depth')}, "
              f"abstentions={el.get('abstentions')}, "
              f"restarts={report.get('restarts', 0)}, "
              f"accounting={terminal_accounting(report)}",
              file=sys.stderr)
    if args.verify_elastic_gang_equivalence:
        eg = report.get("elastic_gangs") or {}
        cmds = eg.get("commands") or {}
        problems = []
        if not eg.get("enabled"):
            problems.append("no elastic_gangs section in the report — "
                            "the mode never engaged")
        if not eg.get("grows"):
            problems.append("no elastic grow fired: gangs never "
                            "expanded beyond min (tune the scenario's "
                            "filler drain)")
        if not eg.get("shrinks"):
            problems.append("no elastic shrink fired: gangs never gave "
                            "capacity back (tune the pressure wave or "
                            "the lifecycle commands)")
        if eg.get("below_min_evictions"):
            problems.append(
                f"{eg['below_min_evictions']} eviction(s) took a gang "
                f"below min outside a full-gang decision")
        if cmds.get("rejected"):
            problems.append(f"{cmds['rejected']} lifecycle command(s) "
                            f"rejected by the funnel")
        if cmds.get("submitted", 0) != cmds.get("applied", 0) \
                + cmds.get("dropped", 0):
            problems.append(f"command ledger does not balance: {cmds}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"] \
                or report["jobs"]["unfinished"]:
            problems.append("not every arrived gang completed: "
                            f"{report['jobs']}")
        if report.get("double_binds"):
            problems.append(f"double-binds under elastic churn: "
                            f"{report['double_binds']}")
        # byte-determinism x2: grow/shrink ordering, funnel consumption,
        # and the topology term are all seeded + virtual-clock driven,
        # so an identical re-run must reproduce the report byte-for-byte
        rerun = run(kill_cycles)
        if deterministic_json(report) != deterministic_json(rerun):
            problems.append("elastic-gang run not byte-deterministic x2")
        if problems:
            for p in problems:
                print(f"elastic-gang-equivalence FAILED: {p}",
                      file=sys.stderr)
            return 1
        print(f"elastic-gang-equivalence OK: grows={eg.get('grows')}, "
              f"shrinks={eg.get('shrinks')}, "
              f"continues={eg.get('elastic_continues')}, "
              f"colocation_rate={eg.get('colocation_rate')}, "
              f"commands={cmds}, "
              f"restarts={report.get('restarts', 0)}, "
              f"accounting={terminal_accounting(report)}",
              file=sys.stderr)
    if args.verify_federated_equivalence:
        import json as _json
        baseline = run([], replicas=1, losses=[], federated=0)
        problems = []
        # contended = anything that can legitimately diverge the
        # aggregate plane from the oracle: seeded kills/lease losses,
        # ack/lease chaos, OR the run itself exercising cross-partition
        # reserves (capacity moved between partitions — timing shifts
        # are the feature)
        contended = bool(kill_cycles or lease_loss
                         or ack_fault_rate or lease_fault_rate
                         or report.get("cross_partition_reserves"))
        if not contended:
            got_json = _json.dumps(oracle_part(report), sort_keys=True,
                                   separators=(",", ":"))
            want_json = _json.dumps(oracle_part(baseline), sort_keys=True,
                                    separators=(",", ":"))
            if got_json != want_json:
                problems.append("non-contended federated aggregate "
                                "decision plane differs from the "
                                "single-scheduler oracle")
        else:
            got = terminal_accounting(report)
            want = terminal_accounting(baseline)
            if got != want:
                problems.append(f"terminal accounting diverged: "
                                f"federated={got} oracle={want}")
        if report.get("double_binds"):
            problems.append(f"cross-partition double-binds in federated "
                            f"run: {report['double_binds']}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("federated run did not complete every "
                            "arrived job")
        if problems:
            for p in problems:
                print(f"federated-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        fed = report.get("federation", {})
        print(f"federated-equivalence OK: partitions={args.federated}, "
              f"restarts={report.get('restarts', 0)}, "
              f"failovers={report.get('failovers', 0)}, "
              f"reserves={report.get('cross_partition_reserves', {})}, "
              f"node_transfers={fed.get('node_transfers', 0)}",
              file=sys.stderr)
    if args.verify_sharded_equivalence:
        from .runner import sharded_sim_conf
        # the unified solver's decisions are mesh-size invariant by
        # construction (per-shard stable top-K -> shard-major merge ->
        # global stable top-K; psum'd gang verdicts over disjoint owner
        # shards), so the full-mesh run must be BYTE-identical to the
        # sharded-devices=1 single-device oracle — no contended/terminal
        # fallback tier exists for this verify on purpose
        oracle = run(kill_cycles, conf=sharded_sim_conf(1))
        problems = []
        if deterministic_json(report) != deterministic_json(oracle):
            problems.append("sharded decision plane differs from the "
                            "single-device oracle (mesh-size invariance "
                            "broken)")
        if report.get("double_binds"):
            problems.append(f"double-binds in sharded run: "
                            f"{report['double_binds']}")
        if problems:
            for p in problems:
                print(f"sharded-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        import jax as _jax
        print(f"sharded-equivalence OK: devices="
              f"{args.sharded_devices or len(_jax.devices())} vs oracle 1, "
              f"accounting={terminal_accounting(report)}", file=sys.stderr)
    if args.verify_mesh_equivalence:
        import json as _json
        from .runner import sharded_sim_conf
        # the degradation ladder's whole contract in one diff: every
        # heal, probe and readmission the chaotic run went through must
        # leave the decision plane BYTE-identical to the fault-free
        # single-device oracle (mesh-size invariance cashes in at every
        # rung), and the CPU-placer rung must never fire while any
        # device survives. Kills compose: the oracle gets the SAME
        # --kill-cycles, so restart accounting matches too.
        oracle = run(kill_cycles, conf=sharded_sim_conf(1), mesh_rate=0.0)
        mesh = report.get("mesh", {})
        problems = []
        got_json = _json.dumps(oracle_part(report), sort_keys=True,
                               separators=(",", ":"))
        want_json = _json.dumps(oracle_part(oracle), sort_keys=True,
                                separators=(",", ":"))
        if got_json != want_json:
            problems.append("mesh-chaos decision plane differs from the "
                            "healthy single-device oracle (degradation "
                            "ladder broke mesh-size invariance)")
        if report.get("double_binds"):
            problems.append(f"double-binds under mesh faults: "
                            f"{report['double_binds']}")
        if not mesh.get("injected"):
            problems.append("no mesh faults injected — the soak is "
                            "vacuous (raise --mesh-fault-rate or run "
                            "more cycles)")
        if mesh.get("heals") == {} and mesh.get("injected"):
            problems.append("faults injected but no mesh heal fired — "
                            "attribution or the heal path is broken")
        if not mesh.get("readmissions"):
            problems.append("no quarantined device was readmitted — the "
                            "probe/readmit arc never completed (run more "
                            "cycles or shorten the window)")
        if mesh.get("cpu_fallback_cycles"):
            problems.append(
                f"{mesh['cpu_fallback_cycles']} cycle(s) fell to the "
                f"CPU-placer rung — only legal with zero healthy "
                f"devices, which this soak never reaches")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("mesh-chaos run did not complete every "
                            "arrived job")
        if problems:
            for p in problems:
                print(f"mesh-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        print(f"mesh-equivalence OK: injected={mesh.get('injected')}, "
              f"heals={mesh.get('heals')}, "
              f"readmissions={mesh.get('readmissions')}, "
              f"rung_cycles={mesh.get('rung_cycles')}, "
              f"restarts={report.get('restarts', 0)}, "
              f"accounting={terminal_accounting(report)}",
              file=sys.stderr)
    if args.verify_pipelined_equivalence:
        import json as _json
        from .report import pipelined_oracle_part
        baseline = run([], pipelined=False, fast_admit=False)
        problems = []
        spec = report.get("speculation", {})
        mode = "byte-identical"
        # strongest claim first: the full decision plane byte-identical
        # to the serial oracle. Conflicts are byte-SAFE by construction
        # (a discarded speculation re-solves serially on the true
        # snapshot), so this usually holds even on conflict-heavy runs;
        # it is REQUIRED whenever nothing could legitimately diverge —
        # no kills, no fast-admit, and zero conflicted/partial commits
        # (the issue's "speculation never conflicts" contract).
        got_json = _json.dumps(pipelined_oracle_part(report),
                               sort_keys=True, separators=(",", ":"))
        want_json = _json.dumps(pipelined_oracle_part(baseline),
                                sort_keys=True, separators=(",", ":"))
        if got_json != want_json:
            diverger = bool(kill_cycles or args.fast_admit
                            or spec.get("conflicts", 0)
                            or spec.get("partial", 0))
            if not diverger:
                problems.append("conflict-free pipelined decision plane "
                                "differs from the serial oracle")
            mode = "terminal"
            got = terminal_accounting(report)
            want = terminal_accounting(baseline)
            if got != want:
                problems.append(f"terminal accounting diverged: "
                                f"pipelined={got} serial={want}")
        if report.get("double_binds"):
            problems.append(f"double-binds in pipelined run: "
                            f"{report['double_binds']}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("pipelined run did not complete every "
                            "arrived job")
        if problems:
            for p in problems:
                print(f"pipelined-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        print(f"pipelined-equivalence OK: speculation={spec}, "
              f"fast_admit={report.get('fast_admit', {})}, "
              f"restarts={report.get('restarts', 0)}, "
              f"ttfb_p99_cycles={report.get('ttfb_p99_cycles')}, "
              f"mode={mode}", file=sys.stderr)
    if args.verify_ha_equivalence:
        import json as _json
        baseline = run([], replicas=1, losses=[], lease_rate=0.0)
        problems = []
        contended = bool(kill_cycles or lease_loss or lease_fault_rate)
        if not contended:
            got_json = _json.dumps(oracle_part(report), sort_keys=True,
                                   separators=(",", ":"))
            want_json = _json.dumps(oracle_part(baseline), sort_keys=True,
                                    separators=(",", ":"))
            if got_json != want_json:
                problems.append("non-contended HA decision plane differs "
                                "from the single-scheduler oracle")
        else:
            got = terminal_accounting(report)
            want = terminal_accounting(baseline)
            if got != want:
                problems.append(f"terminal accounting diverged: "
                                f"ha={got} oracle={want}")
        if report.get("double_binds"):
            problems.append(f"double-binds in HA run: "
                            f"{report['double_binds']}")
        if report["jobs"]["completed"] != report["jobs"]["arrived"]:
            problems.append("HA run did not complete every arrived job")
        if problems:
            for p in problems:
                print(f"ha-equivalence FAILED: {p}", file=sys.stderr)
            return 1
        print(f"ha-equivalence OK: replicas={args.ha}, "
              f"failovers={report.get('failovers', 0)}, "
              f"fenced_rejections={report.get('fenced_rejections', 0)}, "
              f"failover_cycles_max="
              f"{report.get('ha', {}).get('failover_cycles_max', 0)}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
