"""Seeded synthetic workload generators and the named scenario catalog.

Every generator is a pure function of its parameters and ``seed`` — the
same call produces the byte-identical trace, which is what makes
"same trace + seed => identical report" a testable contract (sim/runner).

The cluster-trace literature these mirror: Poisson arrivals with
heavy-tailed (bounded-Pareto) service times and mixed gang sizes are the
standard shape for scheduler evaluation (Gavel replays policy decisions
over such traces, arxiv 2008.09213; Tesserae evaluates placement the same
way, arxiv 2508.04953). ``trace_from_cache`` emits any synthetic BASELINE
world (cache/synthetic.py) as the degenerate all-at-t0 case.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from .trace import TraceEvent, validate_trace

GI = 1 << 30


def _round(x: float, nd: int = 3) -> float:
    return round(float(x), nd)


def _pareto(rng: random.Random, mean: float, alpha: float,
            cap: float) -> float:
    """Bounded Pareto service time with the given mean: heavy-tailed
    durations (a few long jobs dominate machine-time) capped so a single
    sample cannot stretch the simulated horizon unboundedly."""
    xm = mean * (alpha - 1.0) / alpha        # Pareto mean = alpha*xm/(alpha-1)
    u = rng.random()
    return min(xm / ((1.0 - u) ** (1.0 / alpha)), cap)


def synthetic_trace(
        n_jobs: int = 200,
        n_nodes: int = 24,
        *,
        seed: int = 0,
        arrival_rate: float = 4.0,
        duration_mean: float = 6.0,
        duration_cap: float = 60.0,
        tail_alpha: float = 1.8,
        gang_sizes: Sequence[Tuple[int, float]] = ((1, 0.5), (2, 0.3),
                                                   (4, 0.15), (8, 0.05)),
        queues: Sequence[Tuple[str, int]] = (("q1", 3), ("q2", 2),
                                             ("q3", 1)),
        queue_demand: Optional[Sequence[float]] = None,
        cpu_choices: Sequence[int] = (500, 1000, 1500, 2000),
        mem_choices: Sequence[int] = (GI, 3 * GI // 2, 2 * GI),
        priority_choices: Sequence[int] = tuple(range(11)),
        node_cpu_milli: int = 32000,
        node_mem: int = 128 * GI,
        node_pods: int = 110,
        gpus_per_node: int = 0,
        gpus_per_task: int = 0,
        burst_every: float = 0.0,
        burst_size: int = 0,
        extra_events: Sequence[TraceEvent] = (),
) -> List[TraceEvent]:
    """Poisson arrivals, bounded-Pareto durations, mixed gang sizes,
    multi-queue skew.

    ``queue_demand`` weights which queue each arrival lands in (defaults
    to the queue weights themselves — demand proportional to entitlement;
    pass the REVERSE to put the most load on the least-deserving queue,
    which is what drives reclaim). ``burst_every``/``burst_size`` overlay
    synchronized arrival bursts on the Poisson process. ``extra_events``
    splices pre-built events (node drain/fail/restore, hand-built
    arrival waves) into the timeline."""
    rng = random.Random(seed)
    events: List[TraceEvent] = []
    for name, weight in queues:
        events.append(TraceEvent(0.0, "queue_add",
                                 {"name": name, "weight": int(weight)}))
    for i in range(n_nodes):
        events.append(TraceEvent(0.0, "node_add", {
            "name": f"node-{i:05d}", "cpu_milli": int(node_cpu_milli),
            "mem": int(node_mem), "pods": int(node_pods),
            "gpus": int(gpus_per_node)}))

    sizes = [s for s, _ in gang_sizes]
    size_w = [w for _, w in gang_sizes]
    qnames = [n for n, _ in queues]
    demand = list(queue_demand) if queue_demand is not None \
        else [w for _, w in queues]

    arrivals: List[TraceEvent] = []
    t = 0.0
    next_burst = burst_every if burst_every > 0 else float("inf")

    def arrive(j: int, at: float) -> TraceEvent:
        size = rng.choices(sizes, size_w)[0]
        return TraceEvent(_round(at), "job_arrival", {
            "name": f"job-{j:06d}",
            "queue": rng.choices(qnames, demand)[0],
            "priority": rng.choice(list(priority_choices)),
            "tasks": size,
            "min_available": size,
            "cpu_milli": rng.choice(list(cpu_choices)),
            "mem": rng.choice(list(mem_choices)),
            "gpus": int(gpus_per_task),
            "duration": _round(_pareto(rng, duration_mean, tail_alpha,
                                       duration_cap))})

    j = 0
    while j < n_jobs:
        t += rng.expovariate(arrival_rate)
        if t >= next_burst:
            # a synchronized burst lands at the burst tick, then the
            # Poisson stream resumes from it
            for _ in range(min(burst_size, n_jobs - j)):
                arrivals.append(arrive(j, next_burst))
                j += 1
            t = next_burst
            next_burst += burst_every
            continue
        arrivals.append(arrive(j, t))
        j += 1

    merged = sorted(arrivals + list(extra_events),
                    key=lambda ev: (ev.t, ev.kind, ev.data.get("name", "")))
    return validate_trace(events + merged)


def trace_from_cache(cache, duration: float = 30.0) -> List[TraceEvent]:
    """Emit a synthetic cache world (cache/synthetic.baseline_config) as
    the degenerate trace: every queue/node/gang materializes at t=0 and
    every gang runs ``duration`` once admitted. Only all-pending worlds
    convert — pre-placed running tasks have no arrival-event analogue."""
    events: List[TraceEvent] = []
    for q in cache.queues.values():
        events.append(TraceEvent(0.0, "queue_add",
                                 {"name": q.name, "weight": int(q.weight)}))
    for n in cache.nodes.values():
        events.append(TraceEvent(0.0, "node_add", {
            "name": n.name, "cpu_milli": int(n.allocatable.cpu),
            "mem": int(n.allocatable.memory),
            "pods": int(n.allocatable.max_task_num or 0),
            "gpus": int(n.allocatable.get("nvidia.com/gpu"))}))
    for job in cache.jobs.values():
        tasks = list(job.tasks.values())
        if any(t.node_name for t in tasks):
            raise ValueError(f"job {job.uid!r} has pre-placed tasks; only "
                             f"all-pending worlds convert to a trace")
        req = tasks[0].resreq
        events.append(TraceEvent(0.0, "job_arrival", {
            "name": job.uid, "queue": job.queue,
            "priority": int(job.priority), "tasks": len(tasks),
            "min_available": int(job.min_available),
            "cpu_milli": int(req.cpu), "mem": int(req.memory),
            "gpus": int(req.get("nvidia.com/gpu")),
            "duration": _round(duration)}))
    return validate_trace(events)


def baseline_trace(name: str, seed: int = 0,
                   duration: float = 30.0) -> List[TraceEvent]:
    """A BASELINE.md config (cache/synthetic.baseline_config) as a trace."""
    from ..cache.synthetic import baseline_config
    cache, _, _ = baseline_config(name, seed=seed)
    return trace_from_cache(cache, duration=duration)


def _flap_events(nodes: Sequence[int], drain_at: float, restore_at: float,
                 fail: Sequence[int] = (), fail_at: float = 0.0):
    out = []
    for i in nodes:
        out.append(TraceEvent(_round(drain_at), "node_drain",
                              {"name": f"node-{i:05d}"}))
        out.append(TraceEvent(_round(restore_at), "node_restore",
                              {"name": f"node-{i:05d}"}))
    for i in fail:
        out.append(TraceEvent(_round(fail_at), "node_fail",
                              {"name": f"node-{i:05d}"}))
    return tuple(out)


def _priority_wave(seed: int, at: float, n: int, queue: str, priority: int,
                   cpu_milli: int, duration: float,
                   sizes: Sequence[Tuple[int, float]] = ((1, 0.6), (2, 0.4)),
                   ) -> Tuple[TraceEvent, ...]:
    """A synchronized wave of high-priority gangs at one instant — the
    preemption driver (names prefixed ``hi-`` to stay disjoint from the
    Poisson stream's)."""
    rng = random.Random(seed ^ 0x9E3779B9)
    out = []
    for i in range(n):
        size = rng.choices([s for s, _ in sizes], [w for _, w in sizes])[0]
        out.append(TraceEvent(_round(at), "job_arrival", {
            "name": f"hi-{i:04d}", "queue": queue, "priority": priority,
            "tasks": size, "min_available": size, "cpu_milli": cpu_milli,
            "mem": GI, "gpus": 0, "duration": _round(duration)}))
    return tuple(out)


def _flash_crowd(seed: int, at: float, n: int,
                 queues: Sequence[str],
                 duration_mean: float = 6.0) -> Tuple[TraceEvent, ...]:
    """The diurnal flash crowd: ``n`` gangs landing in one tight burst
    window, spread round-robin over the queues (names prefixed ``fc-``
    to stay disjoint from the Poisson stream's) — the daytime peak that
    must drive a partition split under ``sim --elastic``."""
    rng = random.Random(seed ^ 0x5EED)
    out = []
    for i in range(n):
        size = rng.choices([1, 2], [0.6, 0.4])[0]
        out.append(TraceEvent(_round(at + 0.01 * i), "job_arrival", {
            "name": f"fc-{i:04d}", "queue": queues[i % len(queues)],
            "priority": 0,
            "tasks": size, "min_available": size,
            "cpu_milli": rng.choice((1000, 2000)), "mem": GI,
            "gpus": 0,
            "duration": _round(rng.uniform(0.5, 2.0) * duration_mean)}))
    return tuple(out)


def _elastic_churn_trace(seed: int) -> List[TraceEvent]:
    """The elastic-gangs acceptance world (docs/design/elastic-gangs.md):
    zoned nodes, min/desired gangs that must flex min -> desired -> min,
    lifecycle commands through the funnel, and node churn.

    Shape: 12 nodes in 3 zones. Eight elastic gangs (6 tasks, min 2,
    desired 6) arrive into a cluster pre-loaded with a rigid filler wave,
    so they admit at min; the filler drains and the grow stage expands
    them toward desired; a second, larger filler wave lands at t=20 and
    starves, driving pressure shrinks back toward min. Two gangs ride
    the Command funnel (a suspend/resume pair and a scale-down/scale-up
    pair) and the cluster churns underneath (two drains + restores, one
    node death). Run under `--elastic-gangs`; the acceptance gate
    asserts every gang completes at >= min, zero double-binds, zero
    below-min evictions outside full-gang decisions, and byte-identical
    reports across repeated runs."""
    rng = random.Random(seed ^ 0xE1A5)
    events: List[TraceEvent] = [
        TraceEvent(0.0, "queue_add", {"name": "q1", "weight": 2}),
        TraceEvent(0.0, "queue_add", {"name": "q2", "weight": 1}),
    ]
    for i in range(12):
        events.append(TraceEvent(0.0, "node_add", {
            "name": f"node-{i:05d}", "cpu_milli": 8000, "mem": 64 * GI,
            "pods": 40, "gpus": 0, "zone": f"z{i // 4}"}))
    rest: List[TraceEvent] = []
    # rigid filler wave 1: saturates enough capacity that the elastic
    # gangs arriving behind it admit at MIN, not desired
    for i in range(10):
        rest.append(TraceEvent(0.5, "job_arrival", {
            "name": f"rf-{i:04d}", "queue": "q2", "priority": 0,
            "tasks": 2, "min_available": 2, "cpu_milli": 2000, "mem": GI,
            "gpus": 0, "duration": _round(rng.uniform(6.0, 10.0))}))
    # the elastic gangs: 6 tasks, min 2, desired 6
    for i in range(8):
        rest.append(TraceEvent(_round(1.0 + 1.5 * i), "job_arrival", {
            "name": f"eg-{i:04d}", "queue": "q1" if i % 2 == 0 else "q2",
            "priority": 0, "tasks": 6, "min_available": 2, "desired": 6,
            "cpu_milli": 1000, "mem": GI, "gpus": 0,
            "duration": _round(rng.uniform(18.0, 30.0))}))
    # rigid filler wave 2: bigger than the free capacity left once the
    # elastic gangs have grown — the starvation that triggers pressure
    # shrinks back toward min
    for i in range(14):
        rest.append(TraceEvent(_round(20.0 + 0.01 * i), "job_arrival", {
            "name": f"rg-{i:04d}", "queue": "q2", "priority": 0,
            "tasks": 2, "min_available": 2, "cpu_milli": 2000, "mem": GI,
            "gpus": 0, "duration": _round(rng.uniform(5.0, 8.0))}))
    # lifecycle verbs through the Command funnel: a suspend/resume pair
    # (the full-gang drain, where below-min is legal) and a scale
    # round-trip (desired 6 -> 2 -> 6)
    rest += [
        TraceEvent(12.0, "job_command",
                   {"name": "eg-0000", "verb": "suspend"}),
        TraceEvent(14.0, "job_command",
                   {"name": "eg-0001", "verb": "scale", "value": 2}),
        TraceEvent(24.0, "job_command",
                   {"name": "eg-0000", "verb": "resume"}),
        TraceEvent(26.0, "job_command",
                   {"name": "eg-0001", "verb": "scale", "value": 6}),
    ]
    # churn: two drains that restore, one node death mid-run
    rest += list(_flap_events((10, 11), drain_at=10.0, restore_at=22.0,
                              fail=(9,), fail_at=16.0))
    rest.sort(key=lambda ev: (ev.t, ev.kind, ev.data.get("name", "")))
    return validate_trace(events + rest)


# The named scenario catalog (docs/simulation.md records each scenario's
# expected report ranges). Each entry is a factory(seed) -> trace plus a
# one-line description; `python -m volcano_tpu.sim --scenario NAME` runs
# one, and policy/perf PRs are judged on these standing worlds.
SCENARIOS: Dict[str, dict] = {
    "smoke": dict(
        description="60 gangs over ~25 virtual seconds on 10 nodes — the "
                    "fast tier-1 determinism world",
        factory=lambda seed: synthetic_trace(
            60, 10, seed=seed, arrival_rate=2.5, duration_mean=4.0,
            duration_cap=20.0),
    ),
    "steady": dict(
        description="2k gangs at 10 jobs/s on 100 nodes — steady-state "
                    "mixed-gang churn",
        factory=lambda seed: synthetic_trace(
            2000, 100, seed=seed, arrival_rate=10.0, duration_mean=8.0),
    ),
    "steady-10k": dict(
        description="10,500 gangs at ~20 jobs/s on 300 nodes, >=500 "
                    "virtual cycles — the acceptance-scale replay",
        factory=lambda seed: synthetic_trace(
            10500, 300, seed=seed, arrival_rate=20.0, duration_mean=8.0,
            duration_cap=60.0),
    ),
    "burst": dict(
        description="Poisson base load with a 40-gang synchronized burst "
                    "every 30 s — queueing-delay tail under bursts",
        factory=lambda seed: synthetic_trace(
            1200, 80, seed=seed, arrival_rate=5.0, duration_mean=8.0,
            burst_every=30.0, burst_size=40),
    ),
    "skew": dict(
        description="a saturated 6-node cluster, 3 queues weighted 9/3/1 "
                    "with demand reversed 1/3/9, uniform job priority — "
                    "overload is reclaim-shaped: the over-share queue's "
                    "gangs get reclaimed and re-queued behind the "
                    "deserving queues (DRF fairness gap under contention)",
        factory=lambda seed: synthetic_trace(
            150, 6, seed=seed, arrival_rate=6.0, duration_mean=15.0,
            duration_cap=40.0, cpu_choices=(2000, 3000, 4000),
            priority_choices=(0,),
            queues=(("q1", 9), ("q2", 3), ("q3", 1)),
            queue_demand=(1, 3, 9)),
    ),
    "preempt-burst": dict(
        description="low-priority gangs saturate one queue, then a "
                    "high-priority wave lands mid-run: bounded priority "
                    "preemption — the wave evicts, runs, leaves, and the "
                    "preempted gangs re-admit and finish",
        factory=lambda seed: synthetic_trace(
            150, 6, seed=seed, arrival_rate=3.5, duration_mean=10.0,
            duration_cap=30.0, cpu_choices=(2000, 3000),
            priority_choices=(0,), queues=(("q1", 1),),
            extra_events=_priority_wave(seed, at=25.0, n=10, queue="q1",
                                        priority=10, cpu_milli=6000,
                                        duration=4.0)),
    ),
    "node-flap": dict(
        description="steady load while 1/4 of the nodes drain and "
                    "restore, and two nodes fail outright mid-run — "
                    "requeue and re-admission behavior",
        factory=lambda seed: synthetic_trace(
            800, 32, seed=seed, arrival_rate=6.0, duration_mean=8.0,
            extra_events=_flap_events(range(0, 8), drain_at=40.0,
                                     restore_at=80.0, fail=(30, 31),
                                     fail_at=60.0)),
    ),
    "ack-chaos": dict(
        description="120 gangs over 4 skew-weighted queues on a "
                    "saturated 8-node cluster (reclaim-shaped "
                    "evictions), with node drains/restores and one "
                    "node death mid-run — the feedback-plane soak "
                    "world: seeded ack delay/drop/dup/reorder/stale "
                    "plus kills must converge to the no-fault terminal "
                    "accounting with bind AND evict acks in flight "
                    "(docs/robustness.md feedback failure model); the "
                    "4 queues shard under --federated 4",
        factory=lambda seed: synthetic_trace(
            120, 8, seed=seed, arrival_rate=5.0, duration_mean=12.0,
            duration_cap=30.0, cpu_choices=(2000, 3000),
            priority_choices=(0,),
            queues=(("q1", 4), ("q2", 2), ("q3", 1), ("q4", 1)),
            queue_demand=(1, 1, 2, 4),
            extra_events=_flap_events(range(0, 2), drain_at=10.0,
                                      restore_at=20.0, fail=(7,),
                                      fail_at=14.0)),
    ),
    "overload-burst": dict(
        description="240 gangs arriving at ~5x the 8-node cluster's "
                    "drain rate over 4 queues with the full priority "
                    "spread — the sustained-overload world for "
                    "--overload-chaos: the cycle budget must defer "
                    "(not collapse), the admission budget must shed "
                    "lowest-priority work first with retry-after "
                    "hints, and EVERY admitted gang must still "
                    "complete once the wave passes "
                    "(docs/robustness.md overload failure model); the "
                    "4 queues shard under --federated 4",
        factory=lambda seed: synthetic_trace(
            240, 8, seed=seed, arrival_rate=40.0, duration_mean=6.0,
            duration_cap=20.0, cpu_choices=(2000, 3000),
            mem_choices=(GI,),
            gang_sizes=((1, 0.5), (2, 0.35), (4, 0.15)),
            queues=(("q1", 2), ("q2", 2), ("q3", 1), ("q4", 1))),
    ),
    "diurnal-flash-crowd": dict(
        description="a quiet Poisson trickle over 6 queues on 8 small "
                    "nodes, then a ~150-gang flash crowd lands at t=15 "
                    "across every queue and the trickle dies back down "
                    "— the elastic-membership world for `sim "
                    "--federated 1 --elastic` with --overload-chaos: "
                    "chronic cycle-budget exhaustion must SPLIT the "
                    "single partition (bounded per-queue depth while "
                    "the crowd drains through admission backpressure "
                    "and starvation reserves), and the emptied spawned "
                    "partitions must MERGE back to one before the run "
                    "ends (docs/federation.md membership protocol)",
        factory=lambda seed: synthetic_trace(
            40, 8, seed=seed, arrival_rate=1.2, duration_mean=5.0,
            duration_cap=12.0,
            gang_sizes=((1, 0.55), (2, 0.35), (4, 0.10)),
            queues=(("q1", 1), ("q2", 1), ("q3", 1), ("q4", 1),
                    ("q5", 1), ("q6", 1)),
            cpu_choices=(1000, 2000), mem_choices=(GI,),
            priority_choices=(0,),
            node_cpu_milli=8000, node_mem=64 * GI, node_pods=40,
            extra_events=_flash_crowd(
                seed, at=15.0, n=150,
                queues=("q1", "q2", "q3", "q4", "q5", "q6"))),
    ),
    "fed-hotspot": dict(
        description="8 queues round-robined over 4 partitions with "
                    "~80% of the demand pinned to the two queues "
                    "partition 0 owns (q1+q5) — globally under "
                    "capacity but a ~2x hot shard: the load-driven "
                    "rebalancer must move a hot queue off partition 0 "
                    "through the journaled move funnel and CONVERGE "
                    "(no operator move_queue, no ping-pong; "
                    "docs/federation.md)",
        factory=lambda seed: synthetic_trace(
            160, 16, seed=seed, arrival_rate=4.5, duration_mean=12.0,
            duration_cap=30.0, gang_sizes=((2, 0.6), (4, 0.4)),
            queues=(("q1", 1), ("q2", 1), ("q3", 1), ("q4", 1),
                    ("q5", 1), ("q6", 1), ("q7", 1), ("q8", 1)),
            queue_demand=(40, 1, 1, 1, 40, 1, 1, 1),
            cpu_choices=(2000,), mem_choices=(GI,),
            priority_choices=(0,)),
    ),
    "fed-smoke": dict(
        description="60 gangs over 4 equal queues on 16 nodes, light "
                    "load — the federated non-contended oracle world: "
                    "sharded 4 ways every partition places its gangs the "
                    "cycle they arrive, so the aggregate decision plane "
                    "must be byte-identical to the single scheduler's",
        factory=lambda seed: synthetic_trace(
            60, 16, seed=seed, arrival_rate=2.0, duration_mean=4.0,
            duration_cap=12.0,
            gang_sizes=((1, 0.5), (2, 0.35), (4, 0.15)),
            queues=(("q1", 1), ("q2", 1), ("q3", 1), ("q4", 1)),
            cpu_choices=(500, 1000), mem_choices=(GI,),
            priority_choices=(0,)),
    ),
    "fed-starve": dict(
        description="4 queues / 8 nodes sharded 4 ways with demand "
                    "pinned to one queue — its 2-node shard saturates "
                    "while the other shards idle, driving the "
                    "cross-partition reserve/transfer protocol "
                    "(docs/federation.md)",
        factory=lambda seed: synthetic_trace(
            80, 8, seed=seed, arrival_rate=3.0, duration_mean=12.0,
            duration_cap=30.0, gang_sizes=((2, 0.6), (4, 0.4)),
            queues=(("q1", 1), ("q2", 1), ("q3", 1), ("q4", 1)),
            queue_demand=(40, 1, 1, 1),
            cpu_choices=(4000, 8000), mem_choices=(GI,),
            priority_choices=(0,)),
    ),
    "federated-1m": dict(
        description="1,000,000 single-task jobs at 2000 jobs/s over 4 "
                    "queues on 16 fat nodes — the sustained "
                    "millions-of-users intake world for `sim "
                    "--federated 4` (slow; ~500 virtual seconds, jobs "
                    "complete within ~2 s so the live set stays small "
                    "while the cumulative count reaches 1M)",
        factory=lambda seed: synthetic_trace(
            1_000_000, 16, seed=seed, arrival_rate=2000.0,
            duration_mean=1.0, duration_cap=2.0,
            gang_sizes=((1, 1.0),),
            queues=(("q1", 1), ("q2", 1), ("q3", 1), ("q4", 1)),
            cpu_choices=(500,), mem_choices=(GI // 4,),
            priority_choices=(0,),
            node_cpu_milli=1_024_000, node_mem=4096 * GI,
            node_pods=70_000),
    ),
    "pipelined-steady": dict(
        description="48 gangs land at ~t0 on 6 small nodes and drain "
                    "over many cycles with durations long enough that "
                    "nothing completes mid-drain — the pipelined shell's "
                    "no-conflict world: every speculation commits and "
                    "--verify-pipelined-equivalence proves the decision "
                    "plane byte-identical to the serial oracle",
        factory=lambda seed: synthetic_trace(
            48, 6, seed=seed, arrival_rate=1000.0, duration_mean=30.0,
            duration_cap=45.0, tail_alpha=4.0,
            gang_sizes=((1, 0.5), (2, 0.35), (4, 0.15)),
            queues=(("q1", 1),), cpu_choices=(1000, 2000),
            mem_choices=(GI,), priority_choices=(0,),
            node_cpu_milli=4000, node_mem=64 * GI, node_pods=50),
    ),
    "pipelined-conflict": dict(
        description="continuous churn on a 3-node sliver — arrivals and "
                    "completions land between almost every pair of "
                    "cycles, so speculation misses often: the "
                    "conflict-heavy world where the pipelined shell must "
                    "stay terminal-equivalent to the serial oracle with "
                    "zero double-binds",
        factory=lambda seed: synthetic_trace(
            120, 3, seed=seed, arrival_rate=4.0, duration_mean=3.0,
            duration_cap=8.0,
            gang_sizes=((1, 0.6), (2, 0.3), (4, 0.1)),
            queues=(("q1", 2), ("q2", 1)), cpu_choices=(1000, 2000),
            mem_choices=(GI,), priority_choices=(0,),
            node_cpu_milli=6000, node_mem=64 * GI, node_pods=40),
    ),
    "elastic-churn": dict(
        description="8 min-2/desired-6 elastic gangs on 12 zoned nodes "
                    "between two rigid filler waves, with suspend/resume "
                    "+ scale commands and node churn — the elastic-gangs "
                    "acceptance world for `sim --elastic-gangs`: gangs "
                    "flex min -> desired -> min, every gang completes at "
                    ">= min, zero double-binds, zero below-min evictions "
                    "outside full-gang decisions, byte-deterministic",
        factory=_elastic_churn_trace,
    ),
    "baseline-tiny": dict(
        description="BASELINE config 1 (1 gang of 3, 10 nodes) as the "
                    "degenerate all-at-t0 trace",
        factory=lambda seed: baseline_trace("tiny", seed=seed),
    ),
    "baseline-1k": dict(
        description="BASELINE config 2 (1k pods / 200 nodes) as the "
                    "degenerate all-at-t0 trace",
        factory=lambda seed: baseline_trace("1k", seed=seed),
    ),
    "baseline-10k": dict(
        description="BASELINE config 3 (10k pods / 2k nodes, 3 queues) as "
                    "the degenerate all-at-t0 trace",
        factory=lambda seed: baseline_trace("10k", seed=seed),
    ),
    "steady-100k": dict(
        description="100,000 pods / 20,000 nodes (BASELINE config '100k') "
                    "as the all-at-t0 trace — the unified sharded solver's "
                    "scale world (slow; run with --sharded, and "
                    "--verify-sharded-equivalence diffs the full-mesh "
                    "decision plane against the sharded-devices:1 "
                    "single-device oracle byte-for-byte)",
        factory=lambda seed: baseline_trace("100k", seed=seed),
    ),
    "mesh-chaos": dict(
        description="140 gangs over ~45 virtual seconds on 16 nodes, "
                    "long enough past the last arrival that every "
                    "quarantine window expires — the mesh fault soak "
                    "world for `sim --mesh-chaos` on the 8-device "
                    "dryrun mesh: seeded per-shard faults quarantine "
                    "chips mid-solve, the mesh heals over the "
                    "survivors, expired windows probe + readmit, and "
                    "--verify-mesh-equivalence proves the decision "
                    "plane byte-identical to the fault-free 1-device "
                    "oracle (docs/robustness.md mesh failure model)",
        factory=lambda seed: synthetic_trace(
            140, 16, seed=seed, arrival_rate=3.5, duration_mean=6.0,
            duration_cap=18.0,
            gang_sizes=((1, 0.5), (2, 0.35), (4, 0.15)),
            queues=(("q1", 2), ("q2", 1)), cpu_choices=(1000, 2000),
            mem_choices=(GI,), priority_choices=(0,),
            node_cpu_milli=6000, node_mem=64 * GI, node_pods=40),
    ),
}


def make_scenario(name: str, seed: int = 0) -> List[TraceEvent]:
    try:
        return SCENARIOS[name]["factory"](seed)
    except KeyError:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(known: {sorted(SCENARIOS)})") from None
