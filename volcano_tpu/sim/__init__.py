"""Trace-driven cluster simulation & replay (docs/simulation.md).

A discrete-event, virtual-clock simulator that drives the REAL scheduler
— the ``Scheduler`` shell, the full configured action pipeline, cache and
executors — against workload traces, with no wall-clock sleeps. The
standing evaluation harness: policy and performance PRs are judged on the
named scenarios in ``sim.workload.SCENARIOS``.

Entry points::

    python -m volcano_tpu.sim --scenario smoke --seed 0
    python -m volcano_tpu.sim --trace run.jsonl --out report.json

    from volcano_tpu.sim import SimRunner, make_scenario
    report = SimRunner(make_scenario("steady", seed=1), seed=1,
                       scenario="steady").run()
"""

from .report import deterministic_json, deterministic_part, to_json
from .runner import SIM_CONF, SimRunner, VirtualClock
from .trace import TraceEvent, load_trace, validate_trace, write_trace
from .workload import (SCENARIOS, baseline_trace, make_scenario,
                       synthetic_trace, trace_from_cache)

__all__ = [
    "SIM_CONF", "SimRunner", "VirtualClock",
    "TraceEvent", "load_trace", "validate_trace", "write_trace",
    "SCENARIOS", "baseline_trace", "make_scenario", "synthetic_trace",
    "trace_from_cache",
    "deterministic_json", "deterministic_part", "to_json",
]
