"""Per-run simulation reports: latency percentiles, utilization, and the
DRF fairness gap, split into a DECISION plane (a pure function of
trace + seed + conf — the determinism contract) and a WALL-CLOCK plane
(``pipeline_e2e_ms``, per-action latency — properties of the host the sim
ran on). ``deterministic_json`` strips the wall-clock plane so two runs
of the same trace compare byte-for-byte.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List

from ..api import TaskStatus

SCHEMA = "volcano-tpu-sim-report/v1"
_ND = 6                                     # float rounding in report JSON


def percentiles(values: Iterable[float],
                ps: Iterable[int] = (50, 95, 99)) -> Dict[str, float]:
    """Nearest-rank percentiles plus mean/max; {} when empty."""
    vs = sorted(values)
    if not vs:
        return {}
    out = {}
    for p in ps:
        ix = min(len(vs) - 1, max(0, int(round(p / 100.0 * len(vs))) - 1))
        out[f"p{p}"] = round(vs[ix], _ND)
    out["mean"] = round(sum(vs) / len(vs), _ND)
    out["max"] = round(vs[-1], _ND)
    return out


def cpu_utilization(cache) -> float:
    """Allocated-CPU fraction over ready nodes (0 when no node is ready)."""
    return cpu_utilization_all([cache])


def mem_utilization(cache) -> float:
    return mem_utilization_all([cache])


def _utilization_all(caches, field: str) -> float:
    """Aggregate utilization over one or more caches holding DISJOINT
    slices of the same cluster (federated partitions: every cache
    mirrors every node, but each accounts only its own partition's
    tasks). Capacity counts each node once (from the first cache that
    has it); usage sums across all caches. A single-cache list degrades
    to the classic per-cache reading."""
    used = total = 0.0
    seen = set()
    for cache in caches:
        for name, node in cache.nodes.items():
            if not node.ready:
                continue
            used += getattr(node.used, field)
            if name not in seen:
                seen.add(name)
                total += getattr(node.allocatable, field)
    return used / total if total else 0.0


def cpu_utilization_all(caches) -> float:
    return _utilization_all(caches, "cpu")


def mem_utilization_all(caches) -> float:
    return _utilization_all(caches, "memory")


def drf_fairness_gap(cache) -> float:
    return drf_fairness_gap_all([cache])


def drf_fairness_gap_all(caches) -> float:
    """Spread of weight-normalized dominant shares across ACTIVE queues
    (queues holding allocations or pending demand): 0 is perfectly fair
    by DRF-with-weights; the gap is max - min of share_q / weight_q where
    share_q is the queue's dominant resource share of cluster capacity
    (drf.go calculate_share semantics). Inactive queues abstain — an
    empty queue's zero share is idleness, not unfairness. Accepts the
    disjoint partition caches of a federated run (jobs are homed in
    exactly one cache; capacity counts each node once), degrading to the
    classic single-cache reading for a one-element list."""
    total_cpu = total_mem = 0.0
    seen = set()
    for cache in caches:
        for name, node in cache.nodes.items():
            if not node.ready or name in seen:
                continue
            seen.add(name)
            total_cpu += node.allocatable.cpu
            total_mem += node.allocatable.memory
    if not total_cpu:
        return 0.0
    alloc: Dict[str, List[float]] = {}
    active: Dict[str, bool] = {}
    for cache in caches:
        for job in cache.jobs.values():
            cpu = mem = 0.0
            pending = False
            for t in job.tasks.values():
                if t.status in (TaskStatus.BOUND, TaskStatus.BINDING,
                                TaskStatus.RUNNING, TaskStatus.ALLOCATED):
                    cpu += t.resreq.cpu
                    mem += t.resreq.memory
                elif t.status == TaskStatus.PENDING:
                    pending = True
            q = alloc.setdefault(job.queue, [0.0, 0.0])
            q[0] += cpu
            q[1] += mem
            active[job.queue] = active.get(job.queue, False) or pending \
                or cpu > 0 or mem > 0
    shares = []
    for quid, (cpu, mem) in alloc.items():
        if not active.get(quid):
            continue
        queue = None
        for cache in caches:
            queue = cache.queues.get(quid)
            if queue is not None:
                break
        weight = max(getattr(queue, "weight", 1) or 1, 1)
        dom = max(cpu / total_cpu, mem / total_mem if total_mem else 0.0)
        shares.append(dom / weight)
    if len(shares) < 2:
        return 0.0
    return max(shares) - min(shares)


def build_report(runner, actions_ms: Dict[tuple, list],
                 wall_s: float, actions_truncated=()) -> dict:
    """Assemble the report dict from a finished SimRunner.

    ``actions_truncated`` names duration series whose observations
    outgrew the bounded in-process metrics ring during the run — their
    percentiles below cover only the newest retained window, not every
    cycle."""
    conf = runner.sched.conf
    acts = {}
    for key, vals in actions_ms.items():
        if len(key) == 2 and key[0] == "action" and vals:
            acts[key[1]] = percentiles(v / 1e3 for v in vals)  # us -> ms
    report = {
        "schema": SCHEMA,
        "scenario": runner.scenario or "trace",
        "seed": runner.seed,
        "conf_actions": list(conf.actions),
        "period_s": runner.period,
        "cycles": runner.cycles,
        "virtual_time_s": round(runner.clock.time(), _ND),
        "trace_events": len(runner.trace),
        "jobs": {
            "arrived": runner.arrived,
            "admitted": len(runner.gang_admission),
            "completed": runner.completed,
            "unfinished": runner.unfinished_jobs(),
        },
        "binds": len(runner.binder.sequence),
        "evicts": len(runner.evictor.sequence),
        "requeues": runner.requeues,
        "dead_letter": runner.dead_letter_total(),
        "action_failures": len(runner.action_failures),
        # crash/restart plane (zero on unkilled runs; deterministic from
        # kill_cycles + kill_seed, so still part of the decision plane)
        "restarts": getattr(runner, "restarts", 0),
        "double_binds": getattr(runner, "double_binds", 0),
        "journal_replayed": dict(getattr(runner, "_journal_replayed", {})),
        # HA plane (docs/robustness.md): leadership transitions and the
        # fencing gate's stale-epoch rejections — deterministic from
        # (trace, seed, kill/lease-loss config), so decision plane
        "failovers": getattr(runner, "failovers", 0),
        "fenced_rejections": runner.fencing_rejections()
        if hasattr(runner, "fencing_rejections")
        else (runner.authority.rejections
              if getattr(runner, "authority", None) is not None else 0),
        # cross-partition reserve/transfer counters (docs/federation.md):
        # part of EVERY report — a non-federated (or non-contended
        # federated) run must report {} here, which is exactly what the
        # federated-equivalence oracle diff checks
        "cross_partition_reserves": runner.reserve_counts()
        if hasattr(runner, "reserve_counts")
        else (dict(runner.ledger.counts)
              if getattr(runner, "ledger", None) is not None else {}),
        "jct_s": percentiles(runner.jct),
        "queueing_delay_s": percentiles(runner.queueing_delay),
        # time-to-first-bind in CYCLE PERIODS (the fast-admit acceptance
        # metric: < 1.0 means gangs bound between full cycles)
        "ttfb_p99_cycles": round(
            percentiles(runner.queueing_delay).get("p99", 0.0)
            / runner.period, _ND) if runner.period else 0.0,
        "gang_admission_s": percentiles(runner.gang_admission),
        "utilization": {
            "cpu_mean": round(_mean(runner.util_cpu), _ND),
            "cpu_peak": round(max(runner.util_cpu, default=0.0), _ND),
            "mem_mean": round(_mean(runner.util_mem), _ND),
        },
        "fairness": {
            "drf_gap_mean": round(_mean(runner.drf_gap), _ND),
            "drf_gap_max": round(max(runner.drf_gap, default=0.0), _ND),
        },
        # the wall-clock plane: host-dependent, excluded from the
        # determinism contract (deterministic_json strips it)
        "wallclock": {
            "pipeline_e2e_ms": percentiles(runner.pipeline_e2e_ms),
            "actions_ms": acts,
            "total_s": round(wall_s, 3),
        },
    }
    if actions_truncated:
        report["wallclock"]["actions_ms_truncated"] = \
            list(actions_truncated)
    if getattr(runner, "store_wired", False):
        # the hostile-store plane (docs/robustness.md store failure
        # model): all seeded — faults injected, retry-funnel absorption,
        # torn-stream recoveries — so this is decision-plane material
        # and byte-reproducible
        report["store"] = runner.store_detail()
    if getattr(runner, "ack_chaos", False):
        # the hostile feedback plane (docs/robustness.md feedback
        # failure model): all seeded + virtual-clock timed, so
        # decision-plane material and byte-reproducible. Only emitted
        # for ack-chaos runs — fault-free reports stay byte-identical
        # to the pre-feedback-plane decision plane.
        report["feedback"] = runner.feedback_stats()
    if getattr(runner, "overload", False):
        # the overload plane (docs/robustness.md overload failure
        # model): cycle-budget exhaustion/deferral, admission shed
        # counts + retry hints, injected bursts. All priced on the
        # deterministic cost model + seeded injector, so decision-plane
        # material — and only emitted on overload runs, so every
        # fault-free scenario stays byte-identical to the pre-overload
        # decision plane.
        report["overload"] = runner.overload_stats()
    if getattr(runner, "mesh_chaos", False):
        # the mesh plane (docs/robustness.md mesh failure model): seeded
        # per-shard faults, heal/quarantine/readmission deltas, the
        # per-rung cycle tally and the never-CPU witness. Seeded
        # injector + virtual-clock windows ⇒ byte-reproducible; only
        # emitted under --mesh-chaos, so every fault-free scenario stays
        # byte-identical to the pre-mesh decision plane.
        report["mesh"] = runner.mesh_stats()
    if getattr(runner, "pipelined_mode", False):
        # deterministic (cycle-logic-driven) but MECHANISM, not decisions:
        # pipelined_oracle_part strips it for the serial-oracle diff
        report["speculation"] = runner.speculation_stats()
    if getattr(runner, "fast_admit_mode", False):
        report["fast_admit"] = runner.fast_admit_stats()
    if getattr(runner, "lifecycle", False):
        # the cluster-causal plane (obs/lifecycle.py + obs/slo.py):
        # per-class latency attribution derived from the job timelines,
        # and the SLO engine's burn-rate evaluation at end-of-run. Both
        # are pure functions of the virtual-time event stream, so
        # decision-plane material — and only emitted under --lifecycle,
        # so every pre-lifecycle scenario stays byte-identical.
        report["latency"] = runner.lifecycle_stats()
        report["slo"] = runner.slo_status()
    if getattr(runner, "elastic_gangs", False) \
            or getattr(runner, "_command_funnel", None) is not None:
        # elastic GANGS (docs/design/elastic-gangs.md — distinct from
        # federation's elastic partition membership): grow/shrink deltas,
        # the never-below-min witness, the elastic-continue accounting,
        # completion-time co-location, and the Command funnel ledger.
        # Only emitted when the mode (or a job_command trace) is live, so
        # every pre-elastic scenario stays byte-identical.
        report["elastic_gangs"] = runner.elastic_gang_stats()
    if getattr(runner, "federated", 0):
        totals = runner.federation_totals() \
            if hasattr(runner, "federation_totals") else {
                "node_transfers": runner.ledger.node_transfers,
                "queue_moves": runner.ledger.queue_moves}
        report["federation"] = {
            "partitions": runner.federated,
            "map": runner.pmap.counts(),
            "map_version": runner.pmap.version,
            "reserves": report["cross_partition_reserves"],
            "node_transfers": totals["node_transfers"],
            "queue_moves": totals["queue_moves"],
            "failover_cycles": list(runner.failover_cycles),
            "failover_cycles_max": max(runner.failover_cycles, default=0),
        }
        if getattr(runner, "store_wired", False):
            report["federation"]["store_backed"] = True
        if getattr(runner, "rebalance", False):
            # load-driven queue moves (federation/rebalance.py):
            # deterministic from published load signals + the virtual
            # clock — the fed-hotspot convergence witness
            report["federation"]["rebalance"] = runner.rebalance_stats()
        if getattr(runner, "elastic", False):
            # load-driven membership (federation/elastic.py): splits,
            # merges, the partition-count trajectory, and the bounded
            # per-queue depth witness — deterministic from published
            # load + the virtual clock (the diurnal-flash-crowd 1→N→1
            # acceptance section)
            report["federation"]["elastic"] = runner.elastic_stats()
    elif getattr(runner, "replicas", None):
        report["ha"] = {
            "replicas": runner.ha_replicas,
            "failover_cycles": list(runner.failover_cycles),
            "failover_cycles_max": max(runner.failover_cycles, default=0),
            "lease_losses": len(getattr(runner, "lease_loss_cycles", ())),
        }
    return report


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def terminal_accounting(report: dict) -> dict:
    """The restart-equivalence contract (docs/robustness.md): the subset
    of the decision plane a killed-and-recovered run must share with an
    unkilled run of the same trace. Kills legitimately reshuffle the
    bind/evict SEQUENCE and stretch latencies; what recovery must
    preserve is the terminal accounting — every arrived gang completes,
    nothing is left behind, and no task was ever double-bound."""
    return {
        "arrived": report["jobs"]["arrived"],
        "completed": report["jobs"]["completed"],
        "unfinished": report["jobs"]["unfinished"],
        "double_binds": report.get("double_binds", 0),
    }


def oracle_part(report: dict) -> dict:
    """The decision plane MINUS the topology-specific sections — what an
    ``--ha N`` (or ``--federated N``) run of a non-contended trace must
    reproduce byte-for-byte against the single-scheduler oracle (the
    acceptance criterion for decision-plane equivalence).
    ``failovers``/``fenced_rejections``/``cross_partition_reserves``
    stay IN: a non-contended run must report 0 / {} for all three, same
    as the oracle."""
    part = deterministic_part(report)
    part.pop("ha", None)
    part.pop("federation", None)
    part.pop("mesh", None)      # chaos mechanism, not decisions — the
    #                             fault-free oracle has no section at all
    return part


def pipelined_oracle_part(report: dict) -> dict:
    """The decision plane a ``--pipelined`` run of a conflict-free trace
    must reproduce byte-for-byte against the serial oracle: everything
    except the speculation/fast-admit mechanism counters (the oracle has
    none) — the DECISIONS (binds, evicts, admissions, fairness,
    utilization, latencies on the virtual clock) must be identical."""
    part = oracle_part(report)
    part.pop("speculation", None)
    part.pop("fast_admit", None)
    return part


def deterministic_part(report: dict) -> dict:
    """The decision plane only: everything byte-reproducible from
    (trace, seed, conf)."""
    return {k: v for k, v in report.items() if k != "wallclock"}


def to_json(report: dict) -> str:
    return json.dumps(report, sort_keys=True, indent=1)


def deterministic_json(report: dict) -> str:
    """Canonical JSON of the decision plane — the byte-identity witness
    the determinism tests (and the acceptance criterion) compare."""
    return json.dumps(deterministic_part(report), sort_keys=True,
                      separators=(",", ":"))
