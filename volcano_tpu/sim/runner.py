"""SimRunner: discrete-event replay of a workload trace through the REAL
scheduler — the actual ``Scheduler`` shell, the full configured action
pipeline (enqueue → allocate → preempt → reclaim → backfill), the real
cache and executors — under a virtual clock with no wall sleeps.

The loop per virtual cycle:

1. apply trace events due at the current virtual time (arrivals,
   node add/drain/fail) and fire due gang completions;
2. ``Scheduler.run_once()`` — one real cycle over the live cache
   (wall-clock time of this call is the run's ``pipeline_e2e_ms`` sample);
3. feed the cycle's side effects back into the cache the way a cluster
   would: newly bound tasks flip RUNNING (the kubelet ack), evicted tasks
   re-queue PENDING (pod delete + controller recreate), gangs that
   reached ``min_available`` members stamp their admission and schedule a
   completion ``duration`` later;
4. advance the virtual clock by one schedule period.

Everything the runner reports splits into two planes: the DECISION plane
(bind/evict sequences, virtual-time JCT/queueing/admission latencies,
utilization, fairness) is a pure function of (trace, seed, conf) — same
inputs reproduce it byte-identically — while the WALL-CLOCK plane
(``pipeline_e2e_ms``, per-action latency) measures this host and is
reported separately (sim/report.py keeps the two apart so determinism
is assertable).

Chaos composes: pass ``binder_wrap``/``evictor_wrap`` (e.g.
``lambda b: ChaosBinder(b, failure_rate=0.2, seed=7)``) and the injected
failures flow through the cache's real rollback + resync machinery; the
runner pins the resync queue's time source to the virtual clock, so even
retry backoff timing is deterministic.

Crash/restart composes too (docs/robustness.md): ``kill_cycles`` names
virtual cycles on which the scheduler process "dies" — at a seeded kill
point (mid-bind/mid-evict before or after the executor ran, or between
cycles) — and restarts: volatile state (resync queue, dead-letter set,
in-flight markers, incremental snapshot + tensor caches) is lost, the
intent journal survives, and startup reconciliation settles the crash
window against the executors' recorded cluster truth before the next
cycle. The run then must converge to the same terminal decision-plane
accounting as an unkilled run, with zero double-binds — the acceptance
soak the CI chaos step drives.
"""

from __future__ import annotations

import heapq
import itertools
import random
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import metrics
from ..api import (JobInfo, NodeInfo, PodGroup, PodGroupPhase, QueueInfo,
                   Resource, TaskInfo, TaskStatus)
from ..cache import SchedulerCache
from ..cache.cache import RateLimitedQueue
from ..cache.executors import (FencedBinder, FencedEvictor,
                               FencingAuthority, SequenceBinder,
                               SequenceEvictor)
from ..cache.journal import IntentJournal, JournalFollower
from ..elastic_gang.membership import (ELASTIC_DESIRED_ANNOTATION,
                                       TOPOLOGY_ZONE_LABEL, is_elastic)
from ..chaos import (AckFaultInjector, KillPointBinder, KillPointEvictor,
                     SimKill)
from ..obs.trace import TRACE as OBS_TRACE
from ..scheduler import ROLE_LEADER, Scheduler
from .trace import TraceEvent
from . import report as report_mod

# The sim's default pipeline: the chart conf's action chain with the
# deterministic host engines (deploy/chart scheduler.conf swaps in the
# TPU engines; pass conf_text to run the sim against those).
SIM_CONF = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

# conf for ``--pipelined`` runs (docs/performance.md): the speculative
# dispatch/await split exists for the fused device engine, so the
# allocate slot runs allocate-tpu (the scan kernel on CPU jax). The
# serial oracle of --verify-pipelined-equivalence runs this SAME conf —
# the comparison isolates the pipeline, not the engine.
PIPELINED_SIM_CONF = """
actions: "enqueue, allocate-tpu, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


# quarantine windows under --mesh-chaos, in VIRTUAL seconds: short
# enough that quarantine → probe → readmit completes within a scenario
# (the default period is 1.0 s/cycle), long enough that a quarantined
# device misses several solves first. run() restores the wall-clock
# defaults when the sim hands the global DEVICE_HEALTH back.
MESH_SIM_COOLDOWN_S = 6.0
MESH_SIM_MAX_COOLDOWN_S = 48.0


def sharded_sim_conf(devices: int = 0) -> str:
    """Conf for ``--sharded`` runs: the pipelined action chain with the
    allocate slot on the unified shard_map engine (ops/unified — nodes
    axis sharded over the mesh, jobs replicated). ``devices`` caps the
    mesh to the first N devices; 0 = the full mesh. Because the unified
    solver's decisions are mesh-size invariant by construction,
    ``devices=1`` IS the single-device oracle —
    --verify-sharded-equivalence byte-diffs the two decision planes."""
    d = int(devices)
    return f"""
actions: "enqueue, allocate-tpu, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
configurations:
- name: allocate-tpu
  arguments:
    engine: tpu-sharded
    sharded-devices: {d}
"""


def elastic_sim_conf(topology_weight: float = 10.0) -> str:
    """Conf for ``--elastic-gangs`` runs: the default action chain with
    the grow-shrink stage between allocate and preempt (elastic gangs
    admit at min, then expand toward desired as capacity frees), the
    elastic-gang policy plugin in tier 1, and the topology compactness
    weight threaded to both the plugin's node_order bonus and the
    allocate engine's batched anchor term. Weight 0 = topology-unaware
    baseline (the co-location comparison run)."""
    w = float(topology_weight)
    return f"""
actions: "enqueue, allocate, grow-shrink, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: elastic-gang
    arguments:
      topology-weight: {w}
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
configurations:
- name: allocate
  arguments:
    topology-weight: {w}
"""


class VirtualClock:
    """Monotonic virtual time: ``sleep`` advances it and returns
    immediately — the scheduler-shell clock hook for simulation (a
    thousand 1 s cycles cost zero wall seconds)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def time(self) -> float:
        return self._now

    def now(self) -> float:
        """Session/timestamp timebase (WallClock.now counterpart): in the
        sim both pacing and timestamps live on the one virtual axis."""
        return self._now

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self._now += seconds


class _AckWire:
    """The cluster→scheduler feedback wire of the direct-mode sim: every
    kubelet/status ack (RUNNING flip, eviction confirmation) the cluster
    owes the scheduler rides this queue, and a seeded
    ``chaos.AckFaultInjector`` reshapes deliveries — latency on the
    virtual clock, drops, duplicates, adjacent-swap reorders, and stale
    replays that land after the placement they confirm is dead. With no
    injector every ack delivers immediately in offer order — byte-
    identical to the pre-feedback-plane sim. The wire is CLUSTER state:
    it survives scheduler kills (the in-flight ledger does not)."""

    __slots__ = ("clock", "injector", "delay_s", "stale_delay_s", "_heap",
                 "_seq", "delivered")

    def __init__(self, clock, injector=None, delay_s: float = 2.5,
                 stale_delay_s: float = 6.5):
        self.clock = clock
        self.injector = injector
        self.delay_s = delay_s
        self.stale_delay_s = stale_delay_s
        # (due, seq, kind, uid, node); seq is a float so a reordered
        # ack can slot between the next two offers (adjacent swap)
        self._heap: List[Tuple[float, float, str, str, str]] = []
        self._seq = 0.0
        self.delivered = 0

    def _next(self) -> float:
        self._seq += 1.0
        return self._seq

    def offer(self, kind: str, uid: str, node: str = "") -> None:
        now = self.clock.time()
        fault = self.injector.roll(kind) \
            if self.injector is not None else None
        seq = self._next()
        if fault == "drop":
            return
        if fault == "delay":
            heapq.heappush(self._heap,
                           (now + self.delay_s, seq, kind, uid, node))
            return
        if fault == "reorder":
            # sorts after the NEXT offered ack (seq n+1) but before the
            # one after it: the adjacent-pair swap
            heapq.heappush(self._heap, (now, seq + 1.5, kind, uid, node))
            return
        heapq.heappush(self._heap, (now, seq, kind, uid, node))
        if fault == "duplicate":
            heapq.heappush(self._heap, (now + self.delay_s, self._next(),
                                        kind, uid, node))
        elif fault == "stale":
            heapq.heappush(self._heap, (now + self.stale_delay_s,
                                        self._next(), kind, uid, node))

    def due(self, now: float) -> List[Tuple[str, str, str]]:
        out = []
        while self._heap and self._heap[0][0] <= now + 1e-9:
            _, _, kind, uid, node = heapq.heappop(self._heap)
            out.append((kind, uid, node))
        self.delivered += len(out)
        return out

    def pending(self) -> int:
        return len(self._heap)


class _Replica:
    """One scheduler replica of the HA control plane: its own cache +
    shell + elector + standby journal follower over the SHARED cluster
    (executors, journal transport, lease store)."""

    __slots__ = ("ix", "gen", "cache", "sched", "elector", "follower")

    def __init__(self, ix: int):
        self.ix = ix
        self.gen = 0
        self.cache = None
        self.sched = None
        self.elector = None
        self.follower = None

    def key(self) -> tuple:
        return (self.ix, self.gen)


class SimRunner:
    def __init__(self, trace: List[TraceEvent],
                 conf_text: Optional[str] = None,
                 period: float = 1.0,
                 seed: int = 0,
                 max_cycles: int = 100000,
                 stall_limit: int = 120,
                 binder_wrap: Optional[Callable] = None,
                 evictor_wrap: Optional[Callable] = None,
                 scenario: Optional[str] = None,
                 kill_cycles: Optional[Sequence[int]] = None,
                 kill_seed: int = 0,
                 journal: Optional[IntentJournal] = None,
                 ha_replicas: int = 1,
                 lease_loss_cycles: Optional[Sequence[int]] = None,
                 federated_partitions: int = 0,
                 pipelined: bool = False,
                 fast_admit: bool = False,
                 store_wired: bool = False,
                 store_fault_rate: float = 0.0,
                 store_fault_seed: Optional[int] = None,
                 store_latency_s: float = 0.05,
                 torn_watches: int = 0,
                 ack_fault_rate: float = 0.0,
                 ack_fault_seed: Optional[int] = None,
                 lease_fault_rate: float = 0.0,
                 lease_fault_seed: Optional[int] = None,
                 cycle_budget_s: float = 0.0,
                 budget_cost_per_task: float = 0.0,
                 admission_depth: int = 0,
                 overload_burst_rate: float = 0.0,
                 overload_seed: Optional[int] = None,
                 rebalance: bool = False,
                 elastic: bool = False,
                 elastic_gangs: bool = False,
                 topology_weight: float = 10.0,
                 mesh_chaos: bool = False,
                 mesh_fault_rate: float = 0.0,
                 mesh_fault_plan: Optional[Dict[str, Sequence[int]]] = None,
                 mesh_fault_seed: Optional[int] = None,
                 lifecycle: bool = False):
        self.trace = list(trace)
        self.period = period
        self.seed = seed
        self.max_cycles = max_cycles
        self.stall_limit = stall_limit
        self.scenario = scenario

        self.clock = VirtualClock()
        self.binder = SequenceBinder()
        self.evictor = SequenceEvictor()
        binder = binder_wrap(self.binder) if binder_wrap else self.binder
        evictor = evictor_wrap(self.evictor) if evictor_wrap else self.evictor
        # crash/restart rig: kill wrappers sit OUTERMOST (outside chaos)
        # so a kill-after-execute still records the inner side effect —
        # the "cluster did it, the scheduler died before learning" window
        self.kill_cycles = set(kill_cycles or ())
        self.kill_seed = kill_seed
        self._kill_rng = random.Random(kill_seed)
        self.restarts = 0
        self.double_binds = 0
        self._live_bound: set = set()
        # cluster-side requeues that undid a bind BEFORE its harvest ran
        # (feedback defers during leadership vacancies; a node death in
        # that window kills a bind the witness has not been read for
        # yet). The late harvest must consume the debt instead of
        # counting the already-dead bind as live — otherwise the next
        # legitimate re-placement reads as a double-bind.
        self._requeue_debt: Dict[str, int] = {}
        self._journal_replayed: Dict[str, int] = {}
        self._kill_binder: Optional[KillPointBinder] = None
        self._kill_evictor: Optional[KillPointEvictor] = None
        self.journal = journal
        # HA mode (docs/robustness.md): N replica schedulers over ONE
        # virtual cluster — shared executors, shared in-memory intent
        # journal (the standby replay transport), shared lease store +
        # fencing authority; exactly one replica holds the lease and
        # schedules, the rest tail the journal warm.
        self.ha_replicas = max(int(ha_replicas), 1)
        # federated mode (docs/federation.md): N PARTITION schedulers —
        # disjoint queue subsets and node shards of one virtual cluster,
        # each partition its own fenced leader (per-partition lease +
        # authority), coordinating only through the shared journal's
        # reserve/transfer protocol. Mutually exclusive with --ha (the
        # two topologies answer different questions).
        self.federated = max(int(federated_partitions or 0), 0)
        if self.federated == 1 and not elastic:
            # one partition == standalone — EXCEPT under elastic
            # membership, where "1" is just today's partition count and
            # the federation machinery must be live to grow it
            self.federated = 0
        if self.federated and self.ha_replicas > 1:
            raise ValueError("ha_replicas and federated_partitions are "
                             "mutually exclusive")
        # pipelined shell + event-driven fast admit (docs/performance.md):
        # single-scheduler topologies only — the pipeline does not carry
        # speculations across leadership or partition boundaries
        self.pipelined_mode = bool(pipelined)
        self.fast_admit_mode = bool(fast_admit)
        if (self.pipelined_mode or self.fast_admit_mode) \
                and (federated_partitions or ha_replicas > 1):
            raise ValueError("pipelined/fast_admit are single-scheduler "
                             "modes (not --ha / --federated)")
        self._spec_mark: Dict[str, float] = {}
        self._fa_mark: Dict[str, float] = {}
        # store-wired mode (docs/simulation.md --store-wired): cluster
        # truth in a real ObjectStore behind the hostile transport of
        # store_transport.py — per-verb seeded faults, torn watch
        # streams, and (with --federated) the store-backed PartitionState
        # CR. Single-scheduler and federated topologies.
        self.store_wired = bool(store_wired)
        self.store_fault_rate = float(store_fault_rate)
        self.store_fault_seed = seed if store_fault_seed is None \
            else store_fault_seed
        self.store_latency_s = float(store_latency_s)
        self.torn_watches = int(torn_watches)
        self.world = None
        self._store_pending: List[Callable] = []
        self._tear_rng = random.Random(self.store_fault_seed ^ 0x51F7)
        self._tear_cycles: List[int] = sorted(
            self._tear_rng.randint(2, 12) for _ in range(self.torn_watches))
        self.torn_watch_events = 0
        self.ledgers: List = []
        # hostile feedback plane (docs/robustness.md feedback failure
        # model): seeded ack faults on the kubelet/status wire. Direct
        # modes fault the runner-level _AckWire; the store-wired variant
        # faults the watch-path RUNNING acks inside each cache's
        # FeedbackChannel instead (they are watch events there, already
        # subject to the torn streams).
        self.ack_fault_rate = float(ack_fault_rate)
        self.ack_fault_seed = seed if ack_fault_seed is None \
            else ack_fault_seed
        if self.ack_fault_rate and ha_replicas > 1:
            raise ValueError("ack chaos supports single-scheduler and "
                             "federated topologies (not --ha: the "
                             "convergence sweep would mask delays)")
        self._ack_injector = AckFaultInjector(
            failure_rate=self.ack_fault_rate, seed=self.ack_fault_seed,
            delay_s=2.5 * period, stale_delay_s=6.5 * period) \
            if self.ack_fault_rate else None
        self._ack_wire = _AckWire(
            self.clock,
            None if store_wired else self._ack_injector,
            delay_s=2.5 * period, stale_delay_s=6.5 * period)
        self._store_ack_injectors: List[AckFaultInjector] = []
        # HA lease path behind the faulted transport (ROADMAP item 5
        # remainder): per-replica Lease CAS traffic rides retry funnel →
        # faulty transport → lease store when a rate is set
        self.lease_fault_rate = float(lease_fault_rate)
        self.lease_fault_seed = seed if lease_fault_seed is None \
            else lease_fault_seed
        self._lease_transports: Dict[int, object] = {}
        if self.store_wired and ha_replicas > 1:
            raise ValueError("store_wired supports single-scheduler and "
                             "federated topologies (not --ha)")
        if self.store_wired and (pipelined or fast_admit):
            raise ValueError("store_wired and pipelined/fast_admit are "
                             "separate modes")
        # overload resilience (docs/robustness.md overload failure
        # model): a per-cycle deadline budget priced by a DETERMINISTIC
        # cost model (budget_cost_per_task virtual seconds per pending
        # task per action — the virtual clock never advances inside a
        # cycle, so exhaustion is a pure function of the decision
        # plane), a bounded admission budget at the front door (shed
        # arrivals re-offer after their retry_after hint, like a
        # well-behaved client), a seeded OverloadInjector layering
        # arrival bursts on the trace, and (federated) the load-driven
        # queue rebalancer. All off by default — fault-free scenarios
        # stay byte-identical to the pre-overload decision plane.
        self.cycle_budget_s = float(cycle_budget_s)
        self.budget_cost_per_task = float(budget_cost_per_task)
        self.admission_depth = int(admission_depth)
        self.overload_burst_rate = float(overload_burst_rate)
        self.overload_seed = seed if overload_seed is None \
            else overload_seed
        self.rebalance = bool(rebalance)
        # elastic membership (docs/federation.md): the partition COUNT
        # itself becomes load-driven — chronically budget-exhausted
        # partitions split, chronically idle ones merge back, through
        # the journaled partition_spawn/partition_retire funnel. The
        # runner is the host supervisor: its spawn/retire hooks build
        # and reap partition shells mid-run.
        self.elastic = bool(elastic)
        if self.elastic and not self.federated:
            raise ValueError("elastic requires federated_partitions")
        # elastic GANGS (docs/design/elastic-gangs.md) — distinct from
        # elastic partition membership above: gang SIZE becomes the
        # decision variable (admit at min, grow toward desired, shrink
        # elastic members first), with lifecycle verbs riding the
        # journaled Command funnel consumed at cycle boundary. Single
        # direct-scheduler topology only: the funnel mutates the one
        # cache that is cluster truth here.
        self.elastic_gangs = bool(elastic_gangs)
        self.topology_weight = float(topology_weight)
        self._wants_commands = any(ev.kind == "job_command"
                                   for ev in self.trace)
        if self.elastic_gangs or self._wants_commands:
            if (self.federated or self.ha_replicas > 1 or self.store_wired
                    or self.pipelined_mode or self.fast_admit_mode):
                raise ValueError(
                    "elastic_gangs / job_command events require the "
                    "direct single-scheduler topology")
        self.overload = bool(self.cycle_budget_s or self.admission_depth
                             or self.overload_burst_rate
                             or self.rebalance)
        self._admission = None
        if self.admission_depth:
            from ..webhooks.backpressure import AdmissionBudget
            self._admission = AdmissionBudget(
                max_queue_depth=self.admission_depth,
                cycle_period_s=period, time_fn=self.clock.time)
        self._overload_inj = None
        if self.overload_burst_rate:
            from ..chaos import OverloadInjector
            self._overload_inj = OverloadInjector(
                burst_rate=self.overload_burst_rate,
                seed=self.overload_seed)
        self._queue_names: List[str] = []
        self.sheds = 0
        self.shed_reasons: Dict[str, int] = {}
        self.readmit_attempts = 0
        self._retry_heap: List[tuple] = []    # (due, seq, arrival dict)
        self._retry_seq = itertools.count()
        self._burst_seq = itertools.count()
        self._adm_charge: Dict[str, tuple] = {}   # jid -> (queue, tasks, B)
        self._drained_tasks = 0
        self._budget_base = {"exhausted": 0, "deferred": 0, "spend": 0.0}
        self._rebalance_moves: List[dict] = []
        self._rebalance_base = {"abstentions": 0, "refused": 0}
        self._rebalancers: Dict[int, object] = {}
        # elastic bookkeeping: live controllers per pid, counters
        # harvested from dead/retired incarnations, the deterministic
        # membership-change audit trail, and the trace specs a newborn
        # partition's cache backfills from (its "relist")
        self._elastics: Dict[int, object] = {}
        self._elastic_base = {"splits": 0, "merges": 0,
                              "abstentions": 0, "refused": 0}
        self._elastic_events: List[dict] = []
        self._partition_peak = self.federated
        self._queue_specs: Dict[str, dict] = {}
        self._node_specs: Dict[str, dict] = {}
        self._unready_nodes: set = set()
        self._cache_by_pid: Dict[int, SchedulerCache] = {}
        self._retired_watch_counts = {"resumes": 0, "relists": 0}
        self._max_queue_depth = 0
        self.pmap = None
        self.ledger = None
        self.registry = None
        self.lease_loss_cycles = set(lease_loss_cycles or ())
        self._lease_rng = random.Random(kill_seed ^ 0x9E3779B9)
        self.failovers = 0
        self.failover_cycles: List[int] = []
        self._vacant_since: Optional[int] = None
        self._leader_key: Optional[tuple] = None
        self._feedback_blocked = False
        self._armed_action: Optional[int] = None
        self._armed_close = False
        self._armed_revoke: Optional[int] = None
        self._had_leader = False
        self._pending_crash_oracle = None
        self.replicas: List[_Replica] = []
        self.authority: Optional[FencingAuthority] = None
        if self.kill_cycles:
            if self.journal is None:
                self.journal = IntentJournal()    # in-memory: survives the
                #                                   simulated process death
            if not self.store_wired:
                # store mode builds its executor chains per scheduler
                # (StoreWorld.build_cache) and interposes kill wrappers
                # there, between the fencing gate and the store chain
                self._kill_binder = binder = KillPointBinder(binder)
                self._kill_evictor = evictor = KillPointEvictor(evictor)
        # ...and so does the device cool-down window, so a composed
        # DeviceFaultInjector re-probes on a deterministic virtual cycle
        # instead of wherever the host's wall clock lands
        from ..device_health import DEVICE_HEALTH
        DEVICE_HEALTH.reset(time_fn=self.clock.time)
        # lifecycle timelines (obs/lifecycle.py): the store records in
        # every mode (it observes, never influences), but the derived
        # report sections (latency/slo) are emitted only under the
        # explicit --lifecycle flag so fault-free decision planes stay
        # byte-identical. Cleared here so back-to-back runs in one
        # process mint the same deterministic event ids.
        self.lifecycle = bool(lifecycle)
        from ..obs.lifecycle import TIMELINE
        self._timeline = TIMELINE
        self._timeline.clear()
        self._slo_engine = None
        if self.lifecycle:
            from ..obs.slo import SLOEngine
            self._slo_engine = SLOEngine(period=period)
        # per-SHARD mesh chaos (docs/robustness.md mesh failure model): a
        # seeded MeshFaultInjector on the allocate fault hook attributes
        # each fault to a live shard, so the per-device lattice
        # quarantines chips and the mesh heals mid-cycle. The quarantine
        # windows run on the virtual clock at a sim-scale length so the
        # full quarantine → probe → readmit arc completes inside a
        # scenario; run() restores the wall-clock defaults. Restarts
        # (_crash_restart) reset the lattice — health is process memory —
        # but NOT the injector: chaos is the universe, it survives.
        self.mesh_fault_rate = float(mesh_fault_rate)
        self.mesh_fault_plan = {k: tuple(v) for k, v in
                                (mesh_fault_plan or {}).items()}
        self.mesh_fault_seed = seed if mesh_fault_seed is None \
            else mesh_fault_seed
        self.mesh_chaos = bool(mesh_chaos or self.mesh_fault_rate
                               or self.mesh_fault_plan)
        self._mesh_injector = None
        self._mesh_section: Optional[dict] = None
        self._mesh_mark = dict(metrics.mesh_counts())
        self.rung_cycles: Dict[int, int] = {}
        if self.mesh_chaos:
            from ..actions import allocate as _alloc_mod
            from ..chaos import MeshFaultInjector
            DEVICE_HEALTH.cooldown_s = MESH_SIM_COOLDOWN_S
            DEVICE_HEALTH.max_cooldown_s = MESH_SIM_MAX_COOLDOWN_S
            rate = self.mesh_fault_rate or (
                None if self.mesh_fault_plan else 0.2)
            self._mesh_injector = MeshFaultInjector(
                self.mesh_fault_plan or {"device_lost": (),
                                         "oom": (), "slow": ()},
                failure_rate=rate, seed=self.mesh_fault_seed)
            _alloc_mod.DEVICE_FAULT_HOOK = self._mesh_injector
        if conf_text is not None:
            self.conf_text = conf_text
        elif self.elastic_gangs:
            self.conf_text = elastic_sim_conf(self.topology_weight)
        elif self.pipelined_mode or self.fast_admit_mode:
            self.conf_text = PIPELINED_SIM_CONF
        else:
            self.conf_text = SIM_CONF
        if self.store_wired:
            from .store_world import StoreWorld
            self.world = StoreWorld(
                self.clock, fault_rate=self.store_fault_rate,
                fault_seed=self.store_fault_seed,
                latency_s=self.store_latency_s,
                n_schedulers=self.federated or 1,
                retry_rng_seed=seed, period=period)
            # the determinism witnesses: executions that REACHED the
            # store, recorded by the shared wrapper inside every
            # scheduler's executor chain (duck-typed .sequence)
            self.binder = self.world.bind_witness
            self.evictor = self.world.evict_witness
            if self.federated:
                self._init_federated_store(binder_wrap, evictor_wrap)
            else:
                self._init_store_single(binder_wrap, evictor_wrap)
        elif self.federated:
            self._init_federated(binder, evictor)
        elif self.ha_replicas > 1:
            self._init_ha(binder, evictor)
        else:
            self.cache = SchedulerCache(binder=binder, evictor=evictor,
                                        default_queue=None,
                                        journal=self.journal)
            # retry backoff runs on virtual time too: a chaos-failed
            # bind's re-attempt lands on a deterministic virtual cycle,
            # not whenever the host happens to get there
            self.cache.resync_queue.time_fn = self.clock.time
            # job ingestion timestamps (schedule_start_timestamp) pin to
            # virtual time with the same injection
            self.cache.time_fn = self.clock.time
            self._pin_feedback(self.cache)
            self.sched = Scheduler(self.cache, conf_text=self.conf_text,
                                   schedule_period=period, clock=self.clock,
                                   rng=random.Random(seed),
                                   pipelined=self.pipelined_mode,
                                   fast_admit=self.fast_admit_mode,
                                   **self._overload_kwargs())
            self.caches = [self.cache]
            self._spec_mark = dict(metrics.speculation_counts())
            self._fa_mark = dict(metrics.fast_admit_counts())

        # elastic-gang bookkeeping: the Command funnel (journaled+fenced
        # mutation path for suspend/resume/scale — survives crash
        # restarts because it holds the CACHE, which is cluster truth
        # here; _crash_restart re-attaches it to the fresh shell), the
        # metric mark for per-run deltas, and the completion-time
        # co-location counters the topology acceptance gate reads
        self._command_funnel = None
        self._commands_submitted = 0
        self._elastic_continues = 0
        self.colocated_gangs = 0
        self.spread_gangs = 0
        self._eg_mark = dict(metrics.elastic_counts())
        if self.elastic_gangs or self._wants_commands:
            from ..elastic_gang import CommandFunnel
            self._command_funnel = CommandFunnel(self.cache)
            self.sched.command_funnel = self._command_funnel

        # decision-plane bookkeeping
        self.arrival_time: Dict[str, float] = {}
        self.duration: Dict[str, float] = {}
        self.task_job: Dict[str, str] = {}
        self.first_bind: Dict[str, float] = {}
        self.admitted_at: Dict[str, float] = {}
        self._admit_epoch: Dict[str, int] = {}
        self.jct: List[float] = []
        self.queueing_delay: List[float] = []
        self.gang_admission: List[float] = []
        self.completed = 0
        self.arrived = 0
        self.requeues = 0
        self.cycles = 0
        self.action_failures: List[Tuple[int, str]] = []
        self._binds_seen = 0
        self._evicts_seen = 0
        self._completions: List[tuple] = []          # (t, seq, uid, epoch)
        self._cseq = itertools.count()
        self._trace_ix = 0
        # per-cycle samples (decision plane: derived from cache state)
        self.util_cpu: List[float] = []
        self.util_mem: List[float] = []
        self.drf_gap: List[float] = []
        # wall-clock plane
        self.pipeline_e2e_ms: List[float] = []

    # -- overload plumbing (docs/robustness.md overload failure model) -------

    def _overload_kwargs(self) -> dict:
        """The scheduler-shell kwargs of the cycle deadline budget —
        passed to EVERY shell construction (incl. crash restarts), so a
        restarted incarnation keeps the same work bound."""
        if not self.cycle_budget_s:
            return {}
        return {"cycle_budget_s": self.cycle_budget_s,
                "budget_cost_fn": self._budget_cost}

    def _budget_cost(self, name: str, ssn) -> float:
        """The deterministic action cost model: each action is priced
        by the pending backlog it walks. A pure function of the session
        snapshot, so budget exhaustion (and the deferral it causes)
        replays byte-identically."""
        from ..api import TaskStatus
        pending = 0
        for job in ssn.jobs.values():
            pending += len(job.task_status_index.get(TaskStatus.PENDING,
                                                     {}))
        return self.budget_cost_per_task * pending

    def _harvest_budget(self, sched) -> None:
        """A shell is about to be replaced (crash restart): fold its
        budget counters into the run totals (they are per-process
        state and die with it)."""
        self._budget_base["exhausted"] += sched.budget_exhausted_total
        self._budget_base["deferred"] += sched.deferred_actions_total
        self._budget_base["spend"] = max(self._budget_base["spend"],
                                         sched.max_cycle_spend_s)

    def budget_stats(self) -> Dict[str, object]:
        scheds = [rep.sched for rep in self.replicas] \
            if self.replicas else [self.sched]
        exhausted = self._budget_base["exhausted"] \
            + sum(s.budget_exhausted_total for s in scheds)
        deferred = self._budget_base["deferred"] \
            + sum(s.deferred_actions_total for s in scheds)
        spend = max([self._budget_base["spend"]]
                    + [s.max_cycle_spend_s for s in scheds])
        return {"budget_s": self.cycle_budget_s,
                "exhausted": exhausted, "deferred_actions": deferred,
                "max_cycle_spend_s": round(spend, 6)}

    def _admit_arrival(self, t: float, d: dict) -> bool:
        """The front door's backpressure gate: charge the arrival
        against the bounded admission budget, or shed it and schedule
        the client's retry at the refusal's retry_after hint. True =
        admitted (proceed with ingestion)."""
        if self._admission is None:
            return True
        from ..webhooks.backpressure import (BackpressureError,
                                             estimate_job_bytes)
        jid = self._jid(d["name"])
        tasks = int(d["tasks"])
        nbytes = estimate_job_bytes(tasks)
        try:
            self._admission.admit_batch({d["queue"]: tasks}, nbytes,
                                        int(d.get("priority", 0)))
        except BackpressureError as exc:
            self.sheds += 1
            self.shed_reasons[exc.reason] = \
                self.shed_reasons.get(exc.reason, 0) + 1
            # lifecycle breadcrumb: the shed IS the job's first timeline
            # event — a gang refused at the door still explains itself
            self._timeline.record(jid, "shed", t=t, reason=exc.reason,
                                  queue=d["queue"])
            heapq.heappush(self._retry_heap,
                           (t + exc.retry_after_s,
                            next(self._retry_seq), dict(d)))
            return False
        self._adm_charge[jid] = (d["queue"], tasks, nbytes)
        return True

    def _credit_admission(self, jid: str) -> None:
        """The gang left the system (completed): release its admission
        budget and feed the drain-throughput EWMA."""
        charge = self._adm_charge.pop(jid, None)
        if charge is None or self._admission is None:
            return
        queue, tasks, nbytes = charge
        self._admission.credit(queue, tasks, nbytes)
        self._drained_tasks += tasks

    def _drain_retries(self, now: float) -> None:
        """Shed clients retry their POSTs once their retry_after hint
        expires — through the same gate, so a still-full queue sheds
        them again with a fresh (larger-backlog-aware) hint."""
        while self._retry_heap and self._retry_heap[0][0] <= now + 1e-9:
            _, _, d = heapq.heappop(self._retry_heap)
            self.readmit_attempts += 1
            self._arrive(now, d)

    def _inject_bursts(self, now: float) -> None:
        """Seeded OverloadInjector flash crowds: extra single-gang jobs
        on top of the trace, offered through the same admission gate as
        any client POST. Bursts ride the TRACE's arrival window only —
        once the trace is exhausted the crowd stops, the shed-retry
        backlog drains, and the run terminates (the "every admitted
        gang completes" witness needs an end)."""
        if self._overload_inj is None or not self._queue_names \
                or self._trace_ix >= len(self.trace):
            return
        n = self._overload_inj.tick()
        GI = 1 << 30
        for _ in range(n):
            spec = self._overload_inj.job_spec(len(self._queue_names))
            name = f"ovl-{next(self._burst_seq):06d}"
            self._arrive(now, {
                "name": name,
                "queue": self._queue_names[spec["queue_ix"]],
                "priority": int(spec["priority"]),
                "tasks": int(spec["tasks"]),
                "min_available": int(spec["tasks"]),
                "cpu_milli": int(spec["cpu_milli"]),
                "mem": GI // 4, "gpus": 0,
                "duration": float(spec["duration"])})

    def overload_stats(self) -> Dict[str, object]:
        """The report's deterministic overload section (only emitted on
        overload runs, sim/report.py)."""
        out: Dict[str, object] = {
            "cycle_budget": self.budget_stats(),
            "shed_total": self.sheds,
            "shed": dict(sorted(self.shed_reasons.items())),
            "readmit_attempts": self.readmit_attempts,
            "retries_pending": len(self._retry_heap),
            "burst_jobs": self._overload_inj.injected
            if self._overload_inj is not None else 0,
        }
        if self._admission is not None:
            out["admission"] = self._admission.detail()
        return out

    def rebalance_stats(self) -> Dict[str, object]:
        moves = list(self._rebalance_moves)
        for ctrl in self._rebalancers.values():
            moves.extend(ctrl.moves)
        moves.sort(key=lambda m: (m["t"], m["queue"]))
        last_t = max((m["t"] for m in moves), default=0.0)
        return {
            "enabled": self.rebalance,
            "moves": moves,
            "move_count": len(moves),
            "last_move_t": last_t,
            "abstentions": self._rebalance_base["abstentions"] + sum(
                c.abstentions for c in self._rebalancers.values()),
            "refused": self._rebalance_base["refused"] + sum(
                c.refused for c in self._rebalancers.values()),
        }

    def _pin_feedback(self, cache: SchedulerCache) -> None:
        """Pin a cache's feedback-plane machinery to the sim: in-flight
        ack deadlines expire on the virtual clock after a few periods
        (so soaks exercise the watchdog), and a watchdog-recovered evict
        ack hands the controller-recreate to the harness."""
        cache.inflight.time_fn = self.clock.time
        cache.inflight.ack_timeout_s = 3.0 * self.period
        cache.feedback.on_watchdog_evict = \
            lambda jid, uid, c=cache: self._watchdog_requeued(c, jid, uid)

    def _watchdog_requeued(self, cache: SchedulerCache, jid: str,
                           uid: str) -> None:
        """A cache's watchdog recovered a LOST eviction ack and requeued
        the member cache-locally: perform the cluster/controller half —
        fan the requeue out to the other replica caches and keep the
        runner's gang bookkeeping consistent (one logical requeue)."""
        if self.store_wired:
            # the controller-recreate path owns both the idempotency
            # guard (recreate_pod refuses when the harvest already
            # recreated the pod — a delete event merely delayed by a
            # torn stream) and the requeue bookkeeping
            self._requeue_task(uid)
            return
        for other in self.caches:
            if other is not cache:
                other.requeue_lost_member(jid, uid, detach=True)
        self._note_requeue(uid)
        self.requeues += 1
        if jid in self.admitted_at:
            del self.admitted_at[jid]
            self._admit_epoch[jid] = self._admit_epoch.get(jid, 0) + 1

    def _note_requeue(self, uid: str) -> None:
        """A cluster-side requeue retired ``uid``'s live placement: drop
        it from the live-bound witness — or, when the undone bind sits
        UNHARVESTED in the executor witness (feedback deferred during a
        leadership vacancy), record debt the late harvest consumes."""
        if uid in self._live_bound:
            self._live_bound.discard(uid)
        elif any(uid == u for u, _ in
                 self.binder.sequence[self._binds_seen:]):
            self._requeue_debt[uid] = self._requeue_debt.get(uid, 0) + 1

    # -- trace/event application --------------------------------------------

    def _apply_trace_until(self, now: float) -> int:
        n = 0
        while self._trace_ix < len(self.trace) \
                and self.trace[self._trace_ix].t <= now + 1e-9:
            self._apply_event(self.trace[self._trace_ix])
            self._trace_ix += 1
            n += 1
        return n

    def _view(self) -> SchedulerCache:
        """The cache whose state the decision-plane samples and global
        bookkeeping read: the current (or most recent) leader's in HA
        mode, THE cache otherwise. All replica caches converge through
        the journal tail + shared feedback, so the choice only matters
        transiently during failover windows — and it is deterministic."""
        return self.caches[self._view_ix] if self.replicas else self.cache

    def view_cache(self) -> SchedulerCache:
        return self._view()

    def _apply_event(self, ev: TraceEvent) -> None:
        """Apply one trace event to EVERY replica cache (the watch stream
        every replica sees) plus the runner's global bookkeeping once."""
        d = ev.data
        if ev.kind == "queue_add" and d["name"] not in self._queue_names:
            # burst-injection routing table (seeded OverloadInjector
            # picks a queue index; watch-stream order = deterministic)
            self._queue_names.append(d["name"])
        # elastic spawns backfill a newborn partition's cache from these
        # recorded specs (the relist a fresh process start performs)
        if ev.kind == "queue_add":
            self._queue_specs[d["name"]] = dict(d)
        elif ev.kind == "node_add":
            self._node_specs[d["name"]] = dict(d)
        elif ev.kind == "node_fail":
            self._node_specs.pop(d["name"], None)
            self._unready_nodes.discard(d["name"])
        elif ev.kind == "node_drain":
            self._unready_nodes.add(d["name"])
        elif ev.kind == "node_restore":
            self._unready_nodes.discard(d["name"])
        if self.pmap is not None:
            # federated: the watch stream also feeds the partition map
            # (deterministic round-robin in stream order)
            if ev.kind == "queue_add":
                self.pmap.register_queue(d["name"])
            elif ev.kind == "node_add":
                self.pmap.register_node(d["name"])
            elif ev.kind == "node_fail":
                self.pmap.forget_node(d["name"])
        if ev.kind == "node_fail":
            self._fail_node(d["name"])
            return
        if ev.kind == "job_arrival":
            self._arrive(ev.t, d)
            return
        if ev.kind == "job_complete":
            jid = self._jid(d["name"])
            if self._job(jid) is not None:
                self._complete_job(jid, ev.t)
            return
        if ev.kind == "job_command":
            # lifecycle verbs never mutate the cache here: they ride the
            # journaled Command funnel and apply at the NEXT cycle
            # boundary, exactly like a kubectl-annotated CR would land
            # through the watch between cycles
            self._command_funnel.submit(d["verb"], self._jid(d["name"]),
                                        d.get("value"))
            self._commands_submitted += 1
            return
        if self.store_wired and ev.kind == "queue_add":
            # store mode: the queue is a CR; caches learn it through
            # their watches. Submission rides the faulted transport and
            # re-queues on failure like any client POST.
            thunk = self.world.submit_queue(0, d)
            try:
                thunk()
            except Exception:
                self._store_pending.append(thunk)
            return
        for cache in self.caches:
            if ev.kind == "queue_add":
                cache.add_queue(QueueInfo(name=d["name"],
                                          weight=d["weight"]))
            elif ev.kind == "node_add":
                # fresh Resource/NodeInfo PER cache: allocatable is shared
                # across clones by the immutability contract, but live
                # caches mutate their NodeInfo accounting independently
                scalars = {"nvidia.com/gpu": float(d["gpus"])} \
                    if d["gpus"] else None
                alloc = Resource(d["cpu_milli"], d["mem"], scalars)
                alloc.max_task_num = d["pods"]
                labels = {TOPOLOGY_ZONE_LABEL: d["zone"]} \
                    if d.get("zone") else None
                cache.add_node(NodeInfo(name=d["name"], allocatable=alloc,
                                        labels=labels))
            elif ev.kind == "node_drain":
                node = cache.nodes.get(d["name"])
                if node is not None:
                    node.ready = False
                    # direct mutation bypasses the cache's own dirty
                    # tracking
                    cache.mark_node_dirty(node.name)
            elif ev.kind == "node_restore":
                node = cache.nodes.get(d["name"])
                if node is not None:
                    node.ready = True
                    cache.mark_node_dirty(node.name)

    def _job(self, uid: str):
        """The live JobInfo for ``uid`` wherever it is homed: the view
        cache in single/HA mode (replicas converge), the owning
        partition's cache in federated mode (ingestion is partitioned —
        a job exists only in its queue's owner)."""
        for cache in self.caches:
            job = cache.jobs.get(uid)
            if job is not None:
                return job
        return None

    def unfinished_jobs(self) -> int:
        if self.federated:
            return sum(len(c.jobs) for c in self.caches)
        return len(self._view().jobs)

    def dead_letter_total(self) -> int:
        if self.federated:
            return sum(len(c.dead_letter) for c in self.caches)
        return len(self._view().dead_letter)

    def fencing_rejections(self) -> int:
        if self.registry is not None:
            return self.registry.rejections()
        return self.authority.rejections if self.authority is not None \
            else 0

    def _arrive(self, t: float, d: dict) -> None:
        if not self._admit_arrival(t, d):
            return                 # shed: the client's retry is queued
        name = d["name"]
        if self.store_wired:
            # informer-path ingestion: the job materializes as
            # PodGroup + pod CRs through the (faulted) transport; the
            # caches learn it from their watch streams. Bookkeeping is
            # stamped at the front door (arrival is when the client
            # tried); a failed submit retries next cycle.
            jid = self._jid(name)
            for i in range(d["tasks"]):
                self.task_job[f"{name}-{i}"] = jid
            self.arrival_time[jid] = t
            self.duration[jid] = d["duration"]
            self.arrived += 1
            self._timeline.record(jid, "arrival", t=t, queue=d["queue"])
            thunk = self.world.submit_job(0, t, d)
            try:
                thunk()
            except Exception:
                self._store_pending.append(thunk)
            return
        caches = self.caches
        if self.federated:
            # partitioned ingestion: the job materializes only in its
            # queue's owning partition (a server-side filtered watch) —
            # which is also what keeps the 1M-job scenario affordable.
            # Looked up BY PID (elastic membership retires pids, so a
            # list index is not an identity)
            pid = self.pmap.owner_of_queue(d["queue"])
            cache = self._cache_by_pid.get(pid)
            caches = [cache if cache is not None else self.caches[0]]
        for cache in caches:
            scalars = {"nvidia.com/gpu": float(d["gpus"])} if d["gpus"] \
                else None
            ann = {ELASTIC_DESIRED_ANNOTATION: str(int(d["desired"]))} \
                if d.get("desired") is not None else None
            pg = PodGroup(name=name, queue=d["queue"],
                          min_member=d["min_available"],
                          phase=PodGroupPhase.PENDING,
                          annotations=ann)
            job = JobInfo(uid=name, name=name, queue=d["queue"],
                          priority=d["priority"],
                          min_available=d["min_available"], podgroup=pg,
                          creation_timestamp=t)
            for i in range(d["tasks"]):
                uid = f"{name}-{i}"
                job.add_task_info(TaskInfo(
                    uid=uid, name=uid, job=name,
                    resreq=Resource(d["cpu_milli"], d["mem"], scalars),
                    creation_timestamp=t + i * 1e-6))
            cache.add_job(job)
        for i in range(d["tasks"]):
            self.task_job[f"{name}-{i}"] = name
        self.arrival_time[name] = t
        self.duration[name] = d["duration"]
        self.arrived += 1
        self._timeline.record(name, "arrival", t=t, queue=d["queue"])

    def _fail_node(self, name: str) -> None:
        """The node dies with its tasks: lost members re-queue PENDING and
        their gang must re-admit (duration restarts — gang semantics: a
        gang below min_available has lost its collective progress)."""
        if self.store_wired:
            if not any(name in c.nodes for c in self.caches):
                return
            # the kubelet dies with its pods: delete + controller
            # recreate against cluster truth; caches follow by watch
            for uid in self.world.pods_on_node(name):
                self.world.delete_pod(uid)
                self._requeue_task(uid, on_node=False)
            for cache in self.caches:
                cache.remove_node(name)
            return
        uids: List[str] = []
        seen: set = set()
        present = False
        for cache in self.caches:
            node = cache.nodes.get(name)
            if node is None:
                continue
            present = True
            for uid in list(node.tasks):
                if uid not in seen:
                    seen.add(uid)
                    uids.append(uid)
        if not present:
            return
        for uid in uids:
            # the lost members ride the same validate-then-requeue
            # resolution the watchdog uses (cache.requeue_lost_member):
            # a member mid-bind on the dying node has its in-flight
            # entry and binding marker resolved WITH the requeue, so the
            # unacked bind cannot strand them — and the stale RUNNING
            # ack still on the wire classifies stale when it lands
            self._requeue_task(uid, on_node=False, lost_node=name)
        for cache in self.caches:
            cache.remove_node(name)

    def _requeue_task(self, uid: str, on_node: bool = True,
                      via_ack: bool = False,
                      lost_node: Optional[str] = None) -> None:
        jid = self.task_job.get(uid, "")
        if self.store_wired:
            # the evicted/killed pod was already deleted cluster-side;
            # the controller recreates it (same logical member) and the
            # caches converge via their watches. recreate_pod refusing
            # (no blueprint: the gang completed; pod present: already
            # recreated) means there is nothing to requeue.
            if not self.world.recreate_pod(uid):
                return
            self._note_requeue(uid)
            self.requeues += 1
            if jid in self.admitted_at:
                del self.admitted_at[jid]
                self._admit_epoch[jid] = self._admit_epoch.get(jid, 0) + 1
            return
        touched_any = False
        for cache in self.caches:
            if via_ack:
                # an eviction confirmation off the ack wire: consumed
                # through the cache's FeedbackChannel normalizer, which
                # drops acks a NEWER bind intent superseded
                touched = cache.feedback.ack_evicted(jid, uid) == "applied"
            else:
                # cluster-initiated loss (node death): validate-then-
                # requeue, resolving in-flight state with the member
                touched = cache.requeue_lost_member(jid, uid,
                                                    lost_node=lost_node,
                                                    detach=on_node)
            touched_any = touched or touched_any
        if not touched_any:
            return
        if not via_ack:
            # cluster-initiated loss (node death): the ack funnel never
            # saw it, so the runner records the requeue milestone itself
            self._timeline.record(jid, "requeue", task=uid,
                                  node=lost_node or None)
        self._note_requeue(uid)
        self.requeues += 1
        if jid in self.admitted_at:
            vjob = self._job(jid)
            if (vjob is not None and is_elastic(vjob)
                    and vjob.ready_task_num() >= max(vjob.min_available, 1)):
                # elastic-continue: the member lost was surplus (a scale/
                # pressure shrink, a preempt victim, or churn above min)
                # and the gang still holds >= min — collective progress
                # survives, the completion timer keeps running. Dropping
                # below min (or any rigid-gang loss) stays a restart.
                self._elastic_continues += 1
            else:
                # the gang dropped below min_available: cancel its pending
                # completion (epoch bump makes it stale) and let it
                # re-admit
                del self.admitted_at[jid]
                self._admit_epoch[jid] = self._admit_epoch.get(jid, 0) + 1

    def _fire_completions_until(self, now: float) -> None:
        while self._completions and self._completions[0][0] <= now + 1e-9:
            t, _, uid, epoch = heapq.heappop(self._completions)
            if self._admit_epoch.get(uid, 0) != epoch \
                    or uid not in self.admitted_at:
                continue                       # stale: gang was broken up
            self._complete_job(uid, t)

    def _complete_job(self, uid: str, t: float) -> None:
        if self.store_wired:
            # cluster-truth completion: pods + PodGroup leave the store;
            # caches drain through their watches (possibly a resumed
            # stream later — staleness, not loss)
            task_uids = sorted(u for u, j in self.task_job.items()
                               if j == uid)
            if not task_uids:
                return
            self.world.complete_job(uid, task_uids)
            for tuid in task_uids:
                self.task_job.pop(tuid, None)
                self._live_bound.discard(tuid)
            self.admitted_at.pop(uid, None)
            self._credit_admission(uid)
            self.jct.append(t - self.arrival_time[uid])
            self._timeline.record(uid, "complete", t=t)
            OBS_TRACE.flow_end("complete", f"job:{uid}")
            self.completed += 1
            return
        vjob = self._job(uid)
        if vjob is None:
            return
        self._note_colocation(vjob)
        uids = list(vjob.tasks)
        for cache in self.caches:
            job = cache.jobs.get(uid)
            if job is None:
                continue
            for task in list(job.tasks.values()):
                cache.delete_task(task)
            cache.remove_job(uid)
        for tuid in uids:
            self.task_job.pop(tuid, None)
            self._live_bound.discard(tuid)
        self.admitted_at.pop(uid, None)
        self._credit_admission(uid)
        self.jct.append(t - self.arrival_time[uid])
        self._timeline.record(uid, "complete", t=t)
        OBS_TRACE.flow_end("complete", f"job:{uid}")
        self.completed += 1

    def _note_colocation(self, vjob) -> None:
        """Completion-time topology witness: did this gang finish with
        all its placed members in ONE zone? Counted only for multi-member
        gangs on fully-zoned placements — the acceptance comparison
        (topology-weight W vs 0) reads colocated/(colocated+spread)."""
        if not self.elastic_gangs:
            return
        zones = []
        view = self._view()
        for task in vjob.tasks.values():
            if not task.node_name:
                continue
            node = view.nodes.get(task.node_name)
            zones.append(node.topology_zone if node is not None else "")
        if len(zones) < 2 or not all(zones):
            return
        if len(set(zones)) == 1:
            self.colocated_gangs += 1
        else:
            self.spread_gangs += 1

    # -- post-cycle feedback ------------------------------------------------

    def _feedback(self, now: float) -> None:
        """Close the loop the way a live cluster would: binds ack to
        RUNNING, evictions delete-and-recreate PENDING, full gangs stamp
        admission and schedule completion. The HARVEST half (reading the
        executor witnesses) is cluster truth and stamps the runner's
        bookkeeping immediately; the ACKS then ride the _AckWire — where
        seeded chaos delays/drops/duplicates/reorders them — and are
        consumed by each cache's FeedbackChannel normalizer (the watch
        stream is cluster-wide, so deliveries fan out to every replica
        cache)."""
        # re-pin the ambient virtual time: feedback runs BETWEEN cycles,
        # so timeline events minted here (running/evicted acks, bind/
        # admitted milestones) carry the feedback instant, not the
        # previous cycle's
        self._timeline.set_context(t=now)
        touched: Dict[str, bool] = {}
        seq = self.binder.sequence
        while self._binds_seen < len(seq):
            uid, host = seq[self._binds_seen]
            self._binds_seen += 1
            # a second cluster-side bind of a task whose first bind is
            # still live (no evict/requeue in between) is a DOUBLE-BIND —
            # the exact corruption the journal + reconciler must prevent
            if uid in self._live_bound:
                self.double_binds += 1
            elif self._requeue_debt.get(uid):
                # this bind was already undone by a cluster event (node
                # death) while feedback was deferred: it is not live
                self._requeue_debt[uid] -= 1
                if not self._requeue_debt[uid]:
                    del self._requeue_debt[uid]
            else:
                self._live_bound.add(uid)
            jid = self.task_job.get(uid)
            if jid is None:
                continue
            placed = any(jid in cache.jobs
                         and uid in cache.jobs[jid].tasks
                         for cache in self.caches)
            if not placed:
                continue
            if not self.store_wired:
                # store mode: the Running ack arrives through the watch
                # stream (possibly after a torn-stream resume) — a wire
                # ack here would mask exactly the staleness the
                # store-chaos soak exists to exercise
                self._ack_wire.offer("running", uid, host)
            if jid not in self.first_bind:
                self.first_bind[jid] = now
                self.queueing_delay.append(now - self.arrival_time[jid])
                # harvested first bind — the same instant queueing_delay
                # samples, so timeline ttfb and the JCT bookkeeping agree
                self._timeline.record(jid, "bind", t=now, node=host)
            touched[jid] = True
        eseq = self.evictor.sequence
        while self._evicts_seen < len(eseq):
            uid = eseq[self._evicts_seen]
            self._evicts_seen += 1
            if self.store_wired:
                self._requeue_task(uid)
            else:
                self._ack_wire.offer("evicted", uid)
        if not self.store_wired:
            for kind, uid, node in self._ack_wire.due(now):
                jid = self.task_job.get(uid)
                if jid is None:
                    continue           # gang completed while the ack flew
                if kind == "running":
                    for cache in self.caches:
                        cache.feedback.ack_running(jid, uid, node)
                else:
                    self._requeue_task(uid, via_ack=True)
        if self.store_wired:
            # torn watch streams can delay the Running acks past the
            # cycle that bound the gang: keep re-checking gangs with
            # binds until they admit, so admission lands on the first
            # cycle the (resumed) cache shows the gang ready
            for jid in self.first_bind:
                if jid not in self.admitted_at:
                    touched.setdefault(jid, True)
        if self.replicas and not self.store_wired:
            # HA only: a failover's handoff reconcile can re-assert a
            # crash-window bind AFTER its kubelet ack was consumed above
            # (the ack arrived while leadership was vacant and feedback
            # deferred) — converge any still-BOUND task the cluster
            # already runs through the normalizer. Deterministic: sorted
            # uid order.
            for uid in sorted(self._live_bound):
                jid = self.task_job.get(uid)
                if jid is None:
                    continue
                for cache in self.caches:
                    cache.feedback.ack_running(jid, uid, node=None,
                                               source="converge")
        for jid in touched:
            job = self._job(jid)
            if job is None or jid in self.admitted_at:
                continue
            if job.min_available > 0 \
                    and job.ready_task_num() >= job.min_available:
                self.admitted_at[jid] = now
                self.gang_admission.append(now - self.arrival_time[jid])
                self._timeline.record(jid, "admitted", t=now)
                epoch = self._admit_epoch.get(jid, 0)
                heapq.heappush(self._completions,
                               (now + self.duration[jid], next(self._cseq),
                                jid, epoch))

    # -- the run loop -------------------------------------------------------

    def _progress_signature(self) -> tuple:
        return (self._trace_ix, self._binds_seen, self._evicts_seen,
                self.completed, self.requeues, self.unfinished_jobs(),
                self._ack_wire.delivered, self._ack_wire.pending(),
                sum(len(c.resync_queue) for c in self.caches),
                sum(len(c.dead_letter) for c in self.caches),
                len(self._retry_heap), self.sheds,
                self.readmit_attempts)

    def _done(self) -> bool:
        return (self._trace_ix >= len(self.trace)
                and not self._completions
                and not self.unfinished_jobs()
                # shed arrivals still waiting out their retry_after
                # hints must land (and complete) before the run ends —
                # "every admitted gang completes" covers retried ones
                and not self._retry_heap
                # drain the ack wire: a delayed/stale replay still in
                # flight must meet the normalizer, not die with the run
                and not self._ack_wire.pending()
                and not any(c.feedback.pending() for c in self.caches)
                # a submitted lifecycle verb must meet its cycle boundary
                # (and be applied or journaled dropped), not die queued
                and (self._command_funnel is None
                     or not self._command_funnel.pending_count())
                # elastic runs end on the SHRUNK membership: spawned
                # partitions idle out and merge back before the run
                # reports terminal accounting (the 1→N→1 witness);
                # stall_limit remains the backstop for a wedged merge
                and (not self.elastic
                     or len(self.replicas) <= max(self.federated, 1)))

    # -- HA control plane (docs/robustness.md) ------------------------------

    def _lease_store_for(self, ix: int):
        """The store a replica's elector sees: the raw lease store, or —
        with ``--lease-fault-rate`` — its Lease CAS traffic behind the
        SAME hostile-transport composition every other scheduler write
        rides (retry funnel → seeded faulty transport → store; ROADMAP
        item 5 remainder). One persistent transport per replica index so
        restarts replay a deterministic fault stream."""
        if not self.lease_fault_rate:
            return self.lease_store
        transport = self._lease_transports.get(ix)
        if transport is None:
            from ..chaos import StoreFaultInjector
            from ..store_transport import (FaultyStoreTransport,
                                           RetryingStoreTransport)
            injector = StoreFaultInjector(
                failure_rate=self.lease_fault_rate,
                seed=self.lease_fault_seed * 7919 + ix,
                latency_s=0.05, sleep_fn=self.clock.sleep)
            transport = RetryingStoreTransport(
                FaultyStoreTransport(self.lease_store, injector,
                                     name=f"lease-{ix}"),
                sleep_fn=self.clock.sleep, time_fn=self.clock.time,
                cycle_budget_s=0.5 * self.period,
                rng=random.Random(self.lease_fault_seed * 31 + ix))
            self._lease_transports[ix] = transport
        return transport

    def _init_ha(self, binder, evictor) -> None:
        """Build the N-replica control plane: shared lease store +
        fencing authority + in-memory journal (the standby transport);
        per-replica cache/shell/elector/follower. The shared executor
        chain (kill/chaos wrappers over the Sequence recorders) is
        wrapped per replica in a fencing gate reading THAT replica's
        elector epoch — a fenced ex-leader's write is rejected before it
        reaches the cluster."""
        from ..store import ObjectStore
        if self.journal is None:
            self.journal = IntentJournal()
        self.lease_store = ObjectStore()
        self.authority = FencingAuthority()
        self._pending_crash_oracle = None
        self.caches: List[SchedulerCache] = []
        self._view_ix = 0
        for ix in range(self.ha_replicas):
            rep = _Replica(ix)
            self._build_replica_cache(rep, binder, evictor)
            self._build_replica_shell(rep)
            self.replicas.append(rep)
            self.caches.append(rep.cache)
        self.cache = self.caches[0]
        self.sched = self.replicas[0].sched

    def _build_replica_cache(self, rep: _Replica, binder, evictor) -> None:
        cache = SchedulerCache(
            binder=FencedBinder(binder,
                                lambda r=rep: r.elector.fencing_epoch,
                                self.authority),
            evictor=FencedEvictor(evictor,
                                  lambda r=rep: r.elector.fencing_epoch,
                                  self.authority),
            default_queue=None, journal=self.journal)
        cache.resync_queue.time_fn = self.clock.time
        cache.time_fn = self.clock.time
        self._pin_feedback(cache)
        rep.cache = cache
        rep.follower = JournalFollower(cache)
        rep.follower.attach(self.journal)

    def _build_replica_shell(self, rep: _Replica) -> None:
        """(Re)build a replica's scheduler shell + elector — fresh on
        construction AND after each simulated process death (the cache
        survives; it stands in for the relist a restart rebuilds)."""
        from ..leaderelection import FlapGuard, LeaderElector
        ident = f"replica-{rep.ix}" if rep.gen == 0 \
            else f"replica-{rep.ix}-g{rep.gen}"
        rep.elector = LeaderElector(
            self._lease_store_for(rep.ix), "vc-scheduler",
            on_started_leading=lambda: None,
            identity=ident,
            lease_duration=1.6 * self.period,
            renew_deadline=1.2 * self.period,
            retry_period=self.period,
            time_fn=self.clock.time, mono_fn=self.clock.time,
            authority=self.authority,
            flap_guard=FlapGuard(cooldown_s=4 * self.period,
                                 max_cooldown_s=16 * self.period,
                                 time_fn=self.clock.time))
        sched = Scheduler(rep.cache, conf_text=self.conf_text,
                          schedule_period=self.period, clock=self.clock,
                          rng=random.Random(self.seed),
                          **self._overload_kwargs())
        sched.attach_elector(rep.elector)
        sched.reconcile_oracle_fn = self._take_crash_oracle
        sched.action_fault_hook = self._mk_action_hook(rep)
        sched.close_fault_hook = self._close_hook
        rep.sched = sched

    def _take_crash_oracle(self):
        oracle, self._pending_crash_oracle = self._pending_crash_oracle, \
            None
        return oracle

    def _mk_action_hook(self, rep: _Replica) -> Callable:
        """Per-replica pre-action hook: the seeded mid-action SimKill and
        the mid-cycle lease revocation both land at action boundaries of
        whoever is LEADING (followers never reach the action loop)."""
        def hook(name: str, ssn) -> None:
            if self._armed_action is not None:
                self._armed_action -= 1
                if self._armed_action <= 0:
                    self._armed_action = None
                    raise SimKill(f"mid-action (before {name})")
            if self._armed_revoke is not None:
                self._armed_revoke -= 1
                if self._armed_revoke <= 0:
                    self._armed_revoke = None
                    rep.elector.revoke()
        return hook

    def _close_hook(self, ssn) -> None:
        if self._armed_close:
            self._armed_close = False
            raise SimKill("inside close_session")

    _HA_EXTRA_KILL_MODES = ("mid_action", "in_close")

    def _arm_kill_ha(self) -> str:
        """HA kill arming: the single-replica kill points plus the two
        adversarial HA-specific ones — mid-solve (a SimKill before a
        seeded action) and inside close_session."""
        mode = self._kill_rng.choice(self._KILL_MODES
                                     + self._HA_EXTRA_KILL_MODES)
        at = self._kill_rng.randint(1, 5)
        if mode == "bind_before":
            self._kill_binder.arm(at, before=True)
        elif mode == "bind_after":
            self._kill_binder.arm(at, before=False)
        elif mode == "evict_before":
            self._kill_evictor.arm(at, before=True)
        elif mode == "evict_after":
            self._kill_evictor.arm(at, before=False)
        elif mode == "mid_action":
            self._armed_action = at
        elif mode == "in_close":
            self._armed_close = True
        return mode

    def _disarm_kills(self) -> None:
        for kb, ke in getattr(self, "_store_kill_wrappers", {}).values():
            kb.disarm()
            ke.disarm()
        if self._kill_binder is not None:
            self._kill_binder.disarm()
        if self._kill_evictor is not None:
            self._kill_evictor.disarm()
        self._armed_action = None
        self._armed_close = False

    def _crash_restart_replica(self, rep: _Replica,
                               kill_mode: Optional[str]) -> None:
        """A replica's scheduler process dies and restarts as a FOLLOWER:
        volatile state is lost, the shared journal and lease store
        survive. The crash-window oracle (kill-MODE-precise, exactly as
        the single-replica restart) is parked for whichever replica next
        acquires the lease — failover IS lease-acquire →
        startup_reconcile → resume. Cluster feedback is deferred while
        leadership is vacant (a real cluster's acks would queue in the
        new leader's informer sync), so reconcile settles the crash
        window before any ack is consumed — the same ordering the
        single-replica restart preserves within one cycle."""
        self._disarm_kills()
        c = rep.cache
        c.binding_tasks.clear()
        c.inflight.clear()
        c.dead_letter.clear()
        metrics.set_dead_letter_size(0)
        c.err_tasks.clear()
        c.resync_queue = RateLimitedQueue(
            max_retries=c.resync_queue.max_retries,
            time_fn=self.clock.time)
        c.mark_all_dirty()
        c.tensor_cache = None
        c._tensor_dirty = set()
        from ..device_health import DEVICE_HEALTH
        DEVICE_HEALTH.reset(time_fn=self.clock.time)
        # fresh incarnation: new identity + elector + shell; the standby
        # follower reseeds from the surviving journal's open-intent set so
        # the coming reconcile acks resolve against it
        rep.gen += 1
        if rep.follower is not None:
            rep.follower.detach()
        rep.follower = JournalFollower(rep.cache)
        rep.follower.attach(self.journal)
        self._harvest_budget(rep.sched)
        self._build_replica_shell(rep)
        cluster_binds = dict(self.binder.sequence[-1:]) \
            if kill_mode == "bind_after" else {}
        etail = tuple(self.evictor.sequence[-1:]) \
            if kill_mode == "evict_after" else ()

        def cluster_evicts(uid: str, tail=etail) -> bool:
            return uid in tail

        self._pending_crash_oracle = (cluster_binds, cluster_evicts)
        self._feedback_blocked = True
        self.restarts += 1

    def _account_leadership(self) -> None:
        """End-of-cycle leadership bookkeeping: failover counting, the
        failover-time-in-cycles samples, the view cache, handoff report
        accounting, and feedback unblocking."""
        leader = None
        for rep in self.replicas:
            if rep.sched.role == ROLE_LEADER and rep.elector.leading:
                leader = rep
                break
        if leader is None:
            if self._leader_key is not None:
                self._leader_key = None
            if self._vacant_since is None:
                self._vacant_since = self.cycles
            return
        self._view_ix = leader.ix
        key = leader.key()
        if key != self._leader_key:
            if self._had_leader:
                # a failover: either across a vacancy (killed leader,
                # lease had to expire) or a direct same-cycle handoff
                # (revocation + immediate takeover) — gap 0 then
                self.failovers += 1
                gap = 0 if self._vacant_since is None \
                    else self.cycles - self._vacant_since
                self.failover_cycles.append(gap)
            self._vacant_since = None
            self._leader_key = key
            self._had_leader = True
            rpt = getattr(leader.sched, "last_handoff_report", None)
            leader.sched.last_handoff_report = None
            if rpt is not None:
                for k, v in rpt.as_dict().items():
                    if v:
                        self._journal_replayed[k] = \
                            self._journal_replayed.get(k, 0) + v
        self._feedback_blocked = False

    def _ha_cycle(self, now: float) -> None:
        """One virtual cycle of the N-replica control plane: seeded kill/
        lease-loss arming, every replica's run_once in replica order
        (followers run their election step and nothing else), leadership
        accounting, then cluster feedback unless deferred by a vacancy."""
        kill_mode: Optional[str] = None
        if self.cycles in self.kill_cycles:
            kill_mode = self._arm_kill_ha()
        if self.cycles in self.lease_loss_cycles:
            # lease-loss injection: the leader is revoked just before a
            # seeded action ordinal — it must abandon its open session at
            # that boundary and demote to fenced
            self._armed_revoke = self._lease_rng.randint(1, 5)
        for transport in self._lease_transports.values():
            transport.new_cycle()
        leader_ran = False
        for rep in self.replicas:
            t0 = time.perf_counter()
            try:
                errors = rep.sched.run_once()
            except SimKill:
                errors = []
                self._crash_restart_replica(rep, kill_mode)
                kill_mode = None
            else:
                if rep.sched.role == ROLE_LEADER:
                    leader_ran = True
                    self.pipeline_e2e_ms.append(
                        (time.perf_counter() - t0) * 1e3)
                    if kill_mode is not None:
                        # the armed kill never fired inside the leader's
                        # cycle (too few side effects, or post_cycle):
                        # clean-boundary death — still a real restart
                        self._crash_restart_replica(rep, "post_cycle")
                        kill_mode = None
            for name, _ in errors:
                self.action_failures.append((self.cycles, name))
        if kill_mode is not None and not leader_ran:
            # a kill was scheduled for a cycle with no leader (vacancy):
            # nothing to kill; disarm so the stale arm cannot fire later
            self._disarm_kills()
        self._armed_revoke = None
        self._account_leadership()
        if not self._feedback_blocked:
            self._feedback(now)

    # -- federated control plane (docs/federation.md) ------------------------

    def _init_federated(self, binder, evictor) -> None:
        """Build the N-partition control plane: a shared PartitionMap +
        reserve ledger + in-memory journal + lease store, and per
        partition a cache (scoped snapshot over its queue subset + node
        shard), a fenced executor gate against its OWN authority (epochs
        namespaced by partition id), a cycle-driven elector on its OWN
        lease, and a PartitionMember riding the scheduler shell's
        federation hooks."""
        from ..cache.executors import FencingRegistry
        from ..federation import PartitionMap, ReserveLedger
        from ..store import ObjectStore
        if self.journal is None:
            self.journal = IntentJournal()
        self.lease_store = ObjectStore()
        self.registry = FencingRegistry()
        self.pmap = PartitionMap(self.federated)
        self.ledger = ReserveLedger(self.pmap, journal=self.journal,
                                    registry=self.registry,
                                    time_fn=self.clock.time,
                                    timeout_s=8 * self.period,
                                    donor_guard=self.elastic)
        self.caches: List[SchedulerCache] = []
        self._view_ix = 0
        self._fed_oracles: Dict[int, tuple] = {}
        self._p_leader_key: Dict[int, Optional[tuple]] = {}
        self._p_vacant: Dict[int, Optional[int]] = {}
        self._p_had: Dict[int, bool] = {}
        # kept for elastic spawns: a newborn partition's executor gate
        # wraps the SAME (possibly kill-wrapped) cluster executors
        self._fed_binder = binder
        self._fed_evictor = evictor
        for pid in range(self.federated):
            rep = _Replica(pid)
            cache = SchedulerCache(
                binder=FencedBinder(binder,
                                    lambda r=rep: r.elector.fencing_epoch,
                                    self.registry.authority(pid)),
                evictor=FencedEvictor(evictor,
                                      lambda r=rep: r.elector.fencing_epoch,
                                      self.registry.authority(pid)),
                default_queue=None, journal=self.journal)
            cache.resync_queue.time_fn = self.clock.time
            cache.time_fn = self.clock.time
            self._pin_feedback(cache)
            cache.snapshot_scope = \
                lambda ci, p=pid: self.pmap.scope(ci, p)
            rep.cache = cache
            self._build_partition_shell(rep)
            self.replicas.append(rep)
            self.caches.append(cache)
            self._cache_by_pid[pid] = cache
            self._p_leader_key[pid] = None
            self._p_vacant[pid] = None
            self._p_had[pid] = False
        self.cache = self.caches[0]
        self.sched = self.replicas[0].sched
        metrics.set_partition_count(len(self.replicas))

    def _build_partition_shell(self, rep: _Replica) -> None:
        """(Re)build one partition's scheduler shell + elector + member
        — fresh on construction AND after a simulated partition death
        (the cache and the shared map/ledger/journal survive)."""
        from ..federation import PartitionMember
        from ..leaderelection import (FlapGuard, LeaderElector,
                                      partition_lease_name)
        pid = rep.ix
        ident = f"fed-p{pid}" if rep.gen == 0 else f"fed-p{pid}-g{rep.gen}"
        rep.elector = LeaderElector(
            self._lease_store_for(pid),
            partition_lease_name("vc-scheduler", pid),
            on_started_leading=lambda: None,
            identity=ident,
            lease_duration=1.6 * self.period,
            renew_deadline=1.2 * self.period,
            retry_period=self.period,
            time_fn=self.clock.time, mono_fn=self.clock.time,
            authority=self.registry.authority(pid),
            flap_guard=FlapGuard(cooldown_s=4 * self.period,
                                 max_cooldown_s=16 * self.period,
                                 time_fn=self.clock.time))
        sched = Scheduler(rep.cache, conf_text=self.conf_text,
                          schedule_period=self.period, clock=self.clock,
                          rng=random.Random(self.seed),
                          **self._overload_kwargs())
        sched.attach_elector(rep.elector)
        sched.reconcile_oracle_fn = \
            lambda p=pid: self._fed_oracles.pop(p, None)
        sched.action_fault_hook = self._mk_action_hook(rep)
        sched.close_fault_hook = self._close_hook
        # store-backed mode gives each partition its OWN map/ledger
        # mirror (federation/store_backed.py); in-process mode shares one
        pmap = getattr(self, "_p_maps", {}).get(pid, self.pmap)
        ledger = getattr(self, "_p_ledgers", {}).get(pid, self.ledger)
        member = PartitionMember(
            pid, pmap, ledger, rep.cache,
            epoch_fn=lambda r=rep: r.elector.fencing_epoch,
            time_fn=self.clock.time,
            starve_after_s=4 * self.period)
        if self.rebalance:
            # load-driven rebalancing (federation/rebalance.py): each
            # partition's controller decides only moves of its OWN
            # queues, off published load signals. A partition restart
            # loses flap-guard state (volatile, like device cool-down)
            # but never the move audit trail — the runner harvests a
            # dying incarnation's moves in _crash_restart_partition.
            from ..federation import RebalanceController
            ctrl = RebalanceController(
                pid, pmap, ledger, rep.cache,
                epoch_fn=lambda r=rep: r.elector.fencing_epoch,
                time_fn=self.clock.time,
                exhausted_fn=lambda s=sched: s.budget_exhausted_total,
                min_depth=8, min_gap=8, ratio=2.0,
                cooldown_s=8 * self.period,
                max_cooldown_s=64 * self.period)
            member.rebalancer = ctrl
            self._rebalancers[pid] = ctrl
        if self.elastic:
            # elastic membership (federation/elastic.py): this
            # partition's controller may split it or drive its merge,
            # with the runner as host supervisor (spawn_fn/retire_fn
            # build and reap shells). A restart loses streak/flap
            # state (volatile) but never the audit counters — the
            # runner harvests a dying incarnation's in
            # _crash_restart_partition; a killed RETIRING partition
            # resumes its drain from the persisted membership state.
            from ..federation import ElasticController
            ectrl = ElasticController(
                pid, pmap, ledger, rep.cache,
                epoch_fn=lambda r=rep: r.elector.fencing_epoch,
                time_fn=self.clock.time,
                exhausted_fn=lambda s=sched: s.budget_exhausted_total,
                spawn_fn=self._spawn_partition,
                retire_fn=self._retire_partition,
                cooldown_s=16 * self.period,
                max_cooldown_s=128 * self.period)
            member.elastic = ectrl
            self._elastics[pid] = ectrl
        sched.federation = member
        rep.sched = sched

    # -- elastic membership hooks (federation/elastic.py) --------------------

    def _spawn_partition(self, pid: int) -> None:
        """Host half of a SPLIT: the journaled ``partition_spawn``
        already minted ``pid`` in the map; build the newborn's cache +
        scheduler shell + per-partition Lease/FencingAuthority — what a
        real deployment's supervisor does when it exec's one more
        partition process. The newborn owns nothing until the split's
        queue moves settle through the drain funnel; its cache
        backfills queues/nodes from the recorded trace specs (direct
        mode — the relist a fresh process runs) or its own filtered
        informers (store mode)."""
        rep = _Replica(pid)
        if self.store_wired:
            from ..federation import (StoreBackedPartitionMap,
                                      StoreBackedReserveLedger,
                                      StorePartitionBackend)
            # the newborn's own hostile store chain: pid-indexed seed
            # derivation, identical to an up-front partition's
            while len(self.world.transports) <= pid:
                self.world.add_scheduler()
            backend = StorePartitionBackend(self.world.transports[pid],
                                            self.federated)
            pmap_p = StoreBackedPartitionMap(backend)
            ledger = StoreBackedReserveLedger(
                pmap_p, backend, journal=self.journal,
                registry=self.registry, time_fn=self.clock.time,
                timeout_s=8 * self.period, donor_guard=self.elastic)
            cache, b, e = self.world.build_cache(
                pid, self._fed_binder_wrap, self._fed_evictor_wrap,
                journal=self.journal,
                event_filter=self._fed_event_filter(pid))
            self._pin_store_feedback(cache, pid)
            if self.kill_cycles:
                kb, ke = KillPointBinder(b), KillPointEvictor(e)
                self._store_kill_wrappers[pid] = (kb, ke)
                b, e = kb, ke
            cache.binder = FencedBinder(
                b, lambda r=rep: r.elector.fencing_epoch,
                self.registry.authority(pid))
            cache.evictor = FencedEvictor(
                e, lambda r=rep: r.elector.fencing_epoch,
                self.registry.authority(pid))
            cache.snapshot_scope = \
                lambda ci, m=pmap_p, p=pid: m.scope(ci, p)
            rep.cache = cache
            ledger.attach_cache(pid, cache)
            self._p_maps[pid] = pmap_p
            self._p_ledgers[pid] = ledger
            self.ledgers.append(ledger)
            # cross-attach (see _init_federated_store): the newborn's
            # mirror learns every live cache, every live mirror learns
            # the newborn's — settle_moves needs the destination cache
            for other_pid, other_cache in self._cache_by_pid.items():
                ledger.attach_cache(other_pid, other_cache)
            for lg in self.ledgers:
                lg.attach_cache(pid, cache)
        else:
            cache = SchedulerCache(
                binder=FencedBinder(
                    self._fed_binder,
                    lambda r=rep: r.elector.fencing_epoch,
                    self.registry.authority(pid)),
                evictor=FencedEvictor(
                    self._fed_evictor,
                    lambda r=rep: r.elector.fencing_epoch,
                    self.registry.authority(pid)),
                default_queue=None, journal=self.journal)
            cache.resync_queue.time_fn = self.clock.time
            cache.time_fn = self.clock.time
            self._pin_feedback(cache)
            cache.snapshot_scope = \
                lambda ci, p=pid: self.pmap.scope(ci, p)
            rep.cache = cache
        # the relist: every queue and node the watch stream has
        # announced so far (jobs arrive only via the move funnel). A
        # real newborn process LISTS before it watches — its informers
        # replay existing objects; the store-wired cache's watch only
        # delivers events from now on, so both modes backfill here.
        cache = rep.cache
        for spec in self._queue_specs.values():
            cache.add_queue(QueueInfo(name=spec["name"],
                                      weight=spec["weight"]))
        for spec in self._node_specs.values():
            scalars = {"nvidia.com/gpu": float(spec["gpus"])} \
                if spec["gpus"] else None
            alloc = Resource(spec["cpu_milli"], spec["mem"],
                             scalars)
            alloc.max_task_num = spec["pods"]
            labels = {TOPOLOGY_ZONE_LABEL: spec["zone"]} \
                if spec.get("zone") else None
            node = NodeInfo(name=spec["name"], allocatable=alloc,
                            labels=labels)
            if spec["name"] in self._unready_nodes:
                node.ready = False
            cache.add_node(node)
        self._build_partition_shell(rep)
        self.replicas.append(rep)
        self.caches.append(rep.cache)
        self._cache_by_pid[pid] = rep.cache
        self._p_leader_key[pid] = None
        self._p_vacant[pid] = None
        self._p_had[pid] = False
        self._partition_peak = max(self._partition_peak,
                                   len(self.replicas))
        self._elastic_events.append(
            {"cycle": self.cycles, "kind": "spawn", "pid": pid})
        metrics.set_partition_count(len(self.replicas))

    def _retire_partition(self, pid: int) -> None:
        """Host half of a MERGE: the journaled ``partition_retire``
        already removed ``pid`` from the map with its ownership fully
        drained; reap the shell, folding every per-process counter the
        report aggregates into the run totals (the same harvest a
        crash restart performs — retirement is just a PLANNED process
        exit). Pids are never reused, so the reaped slot simply
        disappears from the live lists."""
        rep = next((r for r in self.replicas if r.ix == pid), None)
        if rep is None:
            return
        self._harvest_budget(rep.sched)
        ctrl = self._rebalancers.pop(pid, None)
        if ctrl is not None:
            self._rebalance_moves.extend(ctrl.moves)
            self._rebalance_base["abstentions"] += ctrl.abstentions
            self._rebalance_base["refused"] += ctrl.refused
        ectrl = self._elastics.pop(pid, None)
        if ectrl is not None:
            self._elastic_base["splits"] += ectrl.splits
            self._elastic_base["merges"] += ectrl.merges
            self._elastic_base["abstentions"] += ectrl.abstentions
            self._elastic_base["refused"] += ectrl.refused
        if self.store_wired:
            # the retired cache leaves self.caches: bank its stream-
            # recovery counters so store_detail stays whole-run
            mgr = getattr(rep.cache, "watch_manager", None)
            if mgr is not None:
                for w in mgr.watches:
                    self._retired_watch_counts["resumes"] += w.resumes
                    self._retired_watch_counts["relists"] += w.relists
        self.replicas.remove(rep)
        self.caches.remove(rep.cache)
        self._cache_by_pid.pop(pid, None)
        self._p_leader_key.pop(pid, None)
        self._p_vacant.pop(pid, None)
        self._p_had.pop(pid, None)
        self._fed_oracles.pop(pid, None)
        self._elastic_events.append(
            {"cycle": self.cycles, "kind": "retire", "pid": pid})
        metrics.set_partition_count(len(self.replicas))

    def elastic_stats(self) -> Dict[str, object]:
        """The report's deterministic elastic-membership section."""
        totals = dict(self._elastic_base)
        for c in self._elastics.values():
            totals["splits"] += c.splits
            totals["merges"] += c.merges
            totals["abstentions"] += c.abstentions
            totals["refused"] += c.refused
        return {
            "enabled": self.elastic,
            "splits": totals["splits"],
            "merges": totals["merges"],
            "abstentions": totals["abstentions"],
            "refused": totals["refused"],
            "partitions_initial": self.federated,
            "partitions_final": len(self.replicas),
            "partitions_peak": self._partition_peak,
            "max_queue_depth": self._max_queue_depth,
            "events": list(self._elastic_events),
        }

    def _sample_queue_depth(self) -> None:
        """Per-cycle bounded-depth witness of the elastic soak: the
        deepest single queue's pending-task count, maxed over the run."""
        depth = 0
        for cache in self.caches:
            per_q: Dict[str, int] = {}
            for job in cache.jobs.values():
                n = len(job.task_status_index.get(TaskStatus.PENDING,
                                                  {}))
                if n:
                    per_q[job.queue] = per_q.get(job.queue, 0) + n
            if per_q:
                depth = max(depth, max(per_q.values()))
        self._max_queue_depth = max(self._max_queue_depth, depth)

    def _crash_restart_partition(self, rep: _Replica,
                                 kill_mode: Optional[str]) -> None:
        """One partition's scheduler process dies and restarts: volatile
        state is lost, the shared journal/map/ledger/lease store (and
        the cache, standing in for the relist) survive. The kill-MODE-
        precise crash oracle is parked for THIS partition's next leader
        — the other partitions keep scheduling their own subsets, and
        cluster feedback defers until every partition has a leader again
        (the killed partition's handoff reconcile settles its crash
        window before any ack is consumed)."""
        self._disarm_kills()
        c = rep.cache
        c.binding_tasks.clear()
        c.inflight.clear()
        c.dead_letter.clear()
        metrics.set_dead_letter_size(0)
        c.err_tasks.clear()
        c.resync_queue = RateLimitedQueue(
            max_retries=c.resync_queue.max_retries,
            time_fn=self.clock.time)
        c.mark_all_dirty()
        c.tensor_cache = None
        c._tensor_dirty = set()
        from ..device_health import DEVICE_HEALTH
        DEVICE_HEALTH.reset(time_fn=self.clock.time)
        rep.gen += 1
        self._harvest_budget(rep.sched)
        ctrl = self._rebalancers.get(rep.ix)
        if ctrl is not None:
            # the controller dies with the shell: fold its executed
            # moves AND decision counters into the run's totals before
            # the rebuild (same pattern as _harvest_budget)
            self._rebalance_moves.extend(ctrl.moves)
            self._rebalance_base["abstentions"] += ctrl.abstentions
            self._rebalance_base["refused"] += ctrl.refused
        ectrl = self._elastics.get(rep.ix)
        if ectrl is not None:
            # same reaping for the elastic controller; its streak/flap
            # state is volatile but a killed RETIRING partition is NOT
            # lost — the fresh controller resumes the drain from the
            # persisted membership state (elastic.py step())
            self._elastic_base["splits"] += ectrl.splits
            self._elastic_base["merges"] += ectrl.merges
            self._elastic_base["abstentions"] += ectrl.abstentions
            self._elastic_base["refused"] += ectrl.refused
        self._build_partition_shell(rep)
        cluster_binds = dict(self.binder.sequence[-1:]) \
            if kill_mode == "bind_after" else {}
        etail = tuple(self.evictor.sequence[-1:]) \
            if kill_mode == "evict_after" else ()

        def cluster_evicts(uid: str, tail=etail) -> bool:
            return uid in tail

        self._fed_oracles[rep.ix] = (cluster_binds, cluster_evicts)
        self._feedback_blocked = True
        self.restarts += 1

    def _account_partitions(self) -> None:
        """End-of-cycle leadership bookkeeping, per partition: failover
        counting and vacancy gaps (reusing the HA report fields), the
        handoff-report harvest, and feedback unblocking once EVERY
        partition has a live leader."""
        all_lead = True
        for rep in self.replicas:
            pid = rep.ix
            leads = rep.sched.role == ROLE_LEADER and rep.elector.leading
            if not leads:
                all_lead = False
                self._p_leader_key[pid] = None
                if self._p_vacant[pid] is None:
                    self._p_vacant[pid] = self.cycles
                continue
            key = rep.key()
            if key != self._p_leader_key[pid]:
                if self._p_had[pid]:
                    self.failovers += 1
                    gap = 0 if self._p_vacant[pid] is None \
                        else self.cycles - self._p_vacant[pid]
                    self.failover_cycles.append(gap)
                self._p_vacant[pid] = None
                self._p_leader_key[pid] = key
                self._p_had[pid] = True
                rpt = getattr(rep.sched, "last_handoff_report", None)
                rep.sched.last_handoff_report = None
                if rpt is not None:
                    for k, v in rpt.as_dict().items():
                        if v:
                            self._journal_replayed[k] = \
                                self._journal_replayed.get(k, 0) + v
        if all_lead:
            self._feedback_blocked = False

    def _federated_cycle(self, now: float) -> None:
        """One virtual cycle of the N-partition control plane: seeded
        kill arming (the kill fires inside whichever partition's cycle
        trips the armed point; a never-fired arm degenerates to a
        clean-boundary death of a seeded partition), every partition's
        run_once in pid order, leadership accounting, then cluster
        feedback unless a partition vacancy defers it."""
        kill_mode: Optional[str] = None
        boundary_rep = self.replicas[0]
        if self.cycles in self.kill_cycles:
            # the boundary partition is seeded among the LIVE pids —
            # with static membership this is byte-identical to the
            # fixed range draw (live == range(federated)); under
            # elastic it means a kill can land mid-split on a newborn
            # or mid-merge on a retiring partition
            live = self.replicas
            if self.store_wired:
                # store mode builds kill wrappers PER partition (each
                # partition has its own store chain): seed the boundary
                # partition first and arm that partition's wrappers
                boundary_rep = live[self._kill_rng.randint(
                    0, len(live) - 1)]
                self._kill_binder, self._kill_evictor = \
                    self._store_kill_wrappers[boundary_rep.ix]
                kill_mode = self._arm_kill_ha()
            else:
                kill_mode = self._arm_kill_ha()
                boundary_rep = live[self._kill_rng.randint(
                    0, len(live) - 1)]
        if self.cycles in self.lease_loss_cycles:
            self._armed_revoke = self._lease_rng.randint(1, 5)
        for transport in self._lease_transports.values():
            transport.new_cycle()
        fired = False
        # snapshot: a partition's run_once may SPAWN a sibling (runs
        # from the next cycle) or retire ITSELF (already ran this one)
        for rep in list(self.replicas):
            t0 = time.perf_counter()
            try:
                errors = rep.sched.run_once()
            except SimKill:
                errors = []
                self._crash_restart_partition(rep, kill_mode)
                kill_mode = None
                fired = True
            else:
                if rep.sched.role == ROLE_LEADER:
                    self.pipeline_e2e_ms.append(
                        (time.perf_counter() - t0) * 1e3)
            for name, _ in errors:
                self.action_failures.append((self.cycles, name))
        if kill_mode is not None and not fired:
            # the armed kill never fired (too few side effects, or
            # post_cycle): clean-boundary death of the seeded partition
            if boundary_rep not in self.replicas:
                # the seeded partition retired THIS cycle (its merge
                # completed before the arm could fire): the degenerate
                # clean-boundary death lands on the merge sink instead
                boundary_rep = self.replicas[0]
            self._crash_restart_partition(boundary_rep, "post_cycle")
        self._armed_revoke = None
        self._account_partitions()
        if not self._feedback_blocked:
            self._feedback(now)

    # -- store-wired control planes (docs/simulation.md --store-wired) ------

    def _jid(self, name: str) -> str:
        """The job uid a trace job name maps to: store mode ingests jobs
        through the informer path, whose uid is namespace-qualified."""
        return f"default/{name}" if self.store_wired else name

    def _store_inflight_oracle(self, entry):
        """Cluster truth for the store-wired watchdog: the pod's state
        in the RAW store (what a production watchdog would GET through
        its transport)."""
        pod = self.world.store.get("Pod", "default", entry.uid)
        if entry.op == "bind":
            return pod is not None and pod.status.node_name == entry.node
        # the evict took effect iff the pod-as-placed is gone (the
        # controller's recreate is a fresh, unplaced pod)
        return pod is None or not pod.status.node_name

    def _pin_store_feedback(self, cache: SchedulerCache, ix: int) -> None:
        """Store-wired feedback plumbing: virtual ack deadlines, the
        store-truth oracle, and — under ack chaos — the watch-path
        injector on the cache's FeedbackChannel (acks are watch events
        here; the store-wired ack chaos variant)."""
        self._pin_feedback(cache)
        cache.inflight_oracle_fn = self._store_inflight_oracle
        if self._ack_injector is not None:
            inj = AckFaultInjector(
                failure_rate=self.ack_fault_rate,
                seed=self.ack_fault_seed * 7919 + ix,
                delay_s=2.5 * self.period,
                stale_delay_s=6.5 * self.period)
            cache.feedback.attach_injector(inj, self.clock.time)
            self._store_ack_injectors.append(inj)

    def _init_store_single(self, binder_wrap, evictor_wrap) -> None:
        """Single scheduler over the hostile store boundary: the cache
        is informer-fed (resumable watches) and every executor write
        rides retry funnel → faulty transport → store."""
        cache, b, e = self.world.build_cache(
            0, binder_wrap, evictor_wrap, journal=self.journal)
        self._pin_store_feedback(cache, 0)
        if self.kill_cycles:
            self._kill_binder = KillPointBinder(b)
            self._kill_evictor = KillPointEvictor(e)
            cache.binder = self._kill_binder
            cache.evictor = self._kill_evictor
        self.cache = cache
        self.sched = Scheduler(self.cache, conf_text=self.conf_text,
                               schedule_period=self.period,
                               clock=self.clock,
                               rng=random.Random(self.seed),
                               **self._overload_kwargs())
        self.caches = [self.cache]

    def _fed_event_filter(self, pid: int):
        """The server-side filtered watch of a federated deployment:
        Pod/PodGroup events reach only their queue's owning partition.
        Ownership is read from the REGISTRAR map (raw-store
        PartitionState — the server's own view, never torn), so the
        filter stays stable even while a partition's faulted streams
        lag."""
        from ..cache.store_wiring import GROUP_NAME_ANNOTATION

        def filt(kind: str, obj) -> bool:
            if kind == "PodGroup":
                queue = obj.spec.queue
            else:
                group = obj.metadata.annotations.get(
                    GROUP_NAME_ANNOTATION, "")
                pg = self.world.store.get("PodGroup",
                                          obj.metadata.namespace, group)
                queue = pg.spec.queue if pg is not None else None
            if queue is None:
                return pid == 0
            owner = self.pmap.owner_of_queue(queue)
            return (owner if owner is not None else 0) == pid

        return filt

    def _init_federated_store(self, binder_wrap, evictor_wrap) -> None:
        """N partitions over the hostile store boundary, with the
        PartitionMap/ReserveLedger on the PartitionState CR
        (federation/store_backed.py): per partition its OWN hostile
        transport, its own map/ledger mirror over that transport, an
        informer-fed cache filtered to its queue subset, and a fenced
        executor gate — coordinating only through the store and the
        shared journal, exactly the multi-process deployment shape."""
        from ..cache.executors import FencingRegistry
        from ..federation import (StoreBackedPartitionMap,
                                  StoreBackedReserveLedger,
                                  StorePartitionBackend)
        from ..store import ObjectStore
        if self.journal is None:
            self.journal = IntentJournal()
        self.lease_store = ObjectStore()
        self.registry = FencingRegistry()
        # the registrar mirror over the RAW store: trace-stream
        # registration + the server-side ingestion filter + report map
        self._registrar_backend = StorePartitionBackend(self.world.store,
                                                        self.federated)
        self.pmap = StoreBackedPartitionMap(self._registrar_backend)
        self.caches = []
        self._view_ix = 0
        self._fed_oracles = {}
        self._p_leader_key = {}
        self._p_vacant = {}
        self._p_had = {}
        self._p_maps = {}
        self._p_ledgers = {}
        self._store_kill_wrappers = {}
        # kept for elastic spawns: a newborn's store chain takes the
        # same chaos wraps an up-front partition's does
        self._fed_binder_wrap = binder_wrap
        self._fed_evictor_wrap = evictor_wrap
        for pid in range(self.federated):
            rep = _Replica(pid)
            backend = StorePartitionBackend(self.world.transports[pid],
                                            self.federated)
            pmap_p = StoreBackedPartitionMap(backend)
            ledger = StoreBackedReserveLedger(
                pmap_p, backend, journal=self.journal,
                registry=self.registry, time_fn=self.clock.time,
                timeout_s=8 * self.period, donor_guard=self.elastic)
            cache, b, e = self.world.build_cache(
                pid, binder_wrap, evictor_wrap, journal=self.journal,
                event_filter=self._fed_event_filter(pid))
            self._pin_store_feedback(cache, pid)
            if self.kill_cycles:
                kb, ke = KillPointBinder(b), KillPointEvictor(e)
                self._store_kill_wrappers[pid] = (kb, ke)
                b, e = kb, ke
            cache.binder = FencedBinder(
                b, lambda r=rep: r.elector.fencing_epoch,
                self.registry.authority(pid))
            cache.evictor = FencedEvictor(
                e, lambda r=rep: r.elector.fencing_epoch,
                self.registry.authority(pid))
            cache.snapshot_scope = \
                lambda ci, m=pmap_p, p=pid: m.scope(ci, p)
            rep.cache = cache
            ledger.attach_cache(pid, cache)
            self._p_maps[pid] = pmap_p
            self._p_ledgers[pid] = ledger
            self.ledgers.append(ledger)
            self._build_partition_shell(rep)
            self.replicas.append(rep)
            self.caches.append(cache)
            self._cache_by_pid[pid] = cache
            self._p_leader_key[pid] = None
            self._p_vacant[pid] = None
            self._p_had[pid] = False
        # Cross-attach every partition's cache to every ledger mirror:
        # settle_moves does its job surgery on the DESTINATION cache,
        # and _drain_and_transfer waits on every mirror — the in-process
        # stand-in for the relist a real destination process would run.
        for lg in self.ledgers:
            for other_pid, other_cache in self._cache_by_pid.items():
                lg.attach_cache(other_pid, other_cache)
        self.cache = self.caches[0]
        self.sched = self.replicas[0].sched
        self.ledger = self.ledgers[0]
        metrics.set_partition_count(len(self.replicas))

    def _drain_store_pending(self) -> None:
        """Re-run client submissions that failed at the store boundary
        (the client retrying its POSTs next cycle); thunks are
        idempotent — only what is still missing is created."""
        pending, self._store_pending = self._store_pending, []
        for thunk in pending:
            try:
                thunk()
            except Exception:
                self._store_pending.append(thunk)

    def reserve_counts(self) -> Dict[str, int]:
        """Cross-partition reserve counters, aggregated across ledger
        mirrors in store-backed mode (each settlement is counted once,
        by the partition that performed it)."""
        if self.ledgers:
            out: Dict[str, int] = {}
            for lg in self.ledgers:
                for k, v in lg.counts.items():
                    out[k] = out.get(k, 0) + v
            return out
        return dict(self.ledger.counts) if self.ledger is not None else {}

    def federation_totals(self) -> Dict[str, int]:
        ledgers = self.ledgers or ([self.ledger]
                                   if self.ledger is not None else [])
        return {
            "node_transfers": sum(lg.node_transfers for lg in ledgers),
            "queue_moves": sum(lg.queue_moves for lg in ledgers),
        }

    def store_detail(self) -> Dict[str, object]:
        """The report's deterministic store-boundary section."""
        resumes = self._retired_watch_counts["resumes"]
        relists = self._retired_watch_counts["relists"]
        for cache in self.caches:
            mgr = getattr(cache, "watch_manager", None)
            if mgr is not None:
                for w in mgr.watches:
                    resumes += w.resumes
                    relists += w.relists
        for lg in self.ledgers:
            w = lg.backend._watch
            if w is not None:
                resumes += w.resumes
                relists += w.relists
        return {
            "fault_rate": self.store_fault_rate,
            "faults": self.world.faults_detail(),
            "retry_funnel": self.world.retry_detail(),
            "torn_watch_events": self.torn_watch_events,
            "watch_resumes": resumes,
            "watch_relists": relists,
            "pending_submissions": len(self._store_pending),
        }

    # -- crash/restart ------------------------------------------------------

    _KILL_MODES = ("bind_before", "bind_after", "evict_before",
                   "evict_after", "post_cycle")

    def _arm_kill(self) -> str:
        """Pick (seeded) where this cycle's crash lands and arm the
        matching kill point. Returns the mode; "post_cycle" crashes
        cleanly between run_once and the next cycle instead. Pipelined
        runs add the "speculate" mode — the process dies BETWEEN
        speculative dispatch and commit, the window where the pipeline
        must have journaled nothing."""
        modes = self._KILL_MODES + ("speculate",) if self.pipelined_mode \
            else self._KILL_MODES
        mode = self._kill_rng.choice(modes)
        at = self._kill_rng.randint(1, 5)
        if mode == "bind_before":
            self._kill_binder.arm(at, before=True)
        elif mode == "bind_after":
            self._kill_binder.arm(at, before=False)
        elif mode == "evict_before":
            self._kill_evictor.arm(at, before=True)
        elif mode == "evict_after":
            self._kill_evictor.arm(at, before=False)
        elif mode == "speculate":
            def _hook(spec, _sched=self.sched):
                _sched.spec_fault_hook = None
                raise SimKill("between speculative dispatch and commit")
            self.sched.spec_fault_hook = _hook
        return mode

    def _crash_restart(self, kill_mode: Optional[str] = None) -> None:
        """Simulate the scheduler process dying and a fresh incarnation
        starting against the same cluster. The CACHE's object graph
        stands in for what a restart would rebuild from the API server
        (the sim maintains it as cluster truth), so the restart drops
        exactly the state a real process death loses:

        - the resync queue (queued retries die with the process; their
          tasks are PENDING in cluster truth and simply re-place),
        - the dead-letter set and in-flight binding markers,
        - every incremental-snapshot and device-tensor cache
          (mark_all_dirty + tensor drop — the new process starts cold),

        then runs startup reconciliation: the journal's unacked intent
        (the crash window is at most one — side effects are synchronous)
        is settled against the executors' recorded cluster truth, either
        re-asserted into the cache (the cluster executed it) or rolled
        back (it never happened). A fresh Scheduler shell replaces the
        dead one."""
        c = self.cache
        if self._kill_binder is not None:
            self._kill_binder.disarm()
        if self._kill_evictor is not None:
            self._kill_evictor.disarm()
        c.binding_tasks.clear()
        c.inflight.clear()
        c.dead_letter.clear()
        metrics.set_dead_letter_size(0)
        c.err_tasks.clear()
        c.resync_queue = RateLimitedQueue(
            max_retries=c.resync_queue.max_retries,
            time_fn=self.clock.time)
        c.mark_all_dirty()
        c.tensor_cache = None
        c._tensor_dirty = set()
        self._harvest_budget(self.sched)
        self.sched = Scheduler(self.cache, conf_text=self.conf_text,
                               schedule_period=self.period,
                               clock=self.clock,
                               rng=random.Random(self.seed),
                               pipelined=self.pipelined_mode,
                               fast_admit=self.fast_admit_mode,
                               **self._overload_kwargs())
        if self._command_funnel is not None:
            # the funnel outlives the shell (it holds the cache + journal
            # — cluster truth): pending verbs submitted before the crash
            # apply at the fresh incarnation's first cycle boundary
            self.sched.command_funnel = self._command_funnel
        # a process death also resets the device cool-down state machine
        # (it lives in process memory) — and its clock stays virtual
        from ..device_health import DEVICE_HEALTH
        DEVICE_HEALTH.reset(time_fn=self.clock.time)
        # cluster-truth oracle for the crash window: at most ONE intent
        # is unacked (execution is synchronous) and the KILL MODE says
        # whether its executor ran. Only an after-execute kill makes the
        # executor tail the crash-window op; a before-execute kill means
        # nothing executed — matching the tail there would mistake a
        # STALE earlier bind/evict of the same (task, node) pair for the
        # crash-window execution and "repair" a bind the cluster never
        # saw.
        cluster_binds = dict(self.binder.sequence[-1:]) \
            if kill_mode == "bind_after" else {}
        etail = self.evictor.sequence[-1:] \
            if kill_mode == "evict_after" else []

        def cluster_evicts(uid: str) -> bool:
            return uid in etail

        report = self.sched.startup_reconcile(cluster_binds, cluster_evicts)
        if report is not None:
            for k, v in report.as_dict().items():
                if v:
                    self._journal_replayed[k] = \
                        self._journal_replayed.get(k, 0) + v
        self.restarts += 1

    def speculation_stats(self) -> Dict[str, object]:
        """This run's speculation outcome deltas (the process-global
        counters are marked at construction). hit_rate counts committed
        speculations (full hits + partial replays) over all outcomes."""
        now = metrics.speculation_counts()
        d = {k: int(now.get(k, 0) - self._spec_mark.get(k, 0))
             for k in set(now) | set(self._spec_mark)}
        hits = d.get("hit", 0)
        partial = d.get("partial", 0)
        conflicts = d.get("conflict", 0)
        total = hits + partial + conflicts
        return {"hits": hits, "partial": partial, "conflicts": conflicts,
                "hit_rate": round((hits + partial) / total, 4)
                if total else 0.0}

    def elastic_gang_stats(self) -> Dict[str, object]:
        """The report's deterministic elastic-gangs section: per-run
        grow/shrink deltas (process-global counters marked at
        construction), the never-below-min witness (expected 0), the
        elastic-continue vs duration-restart split, completion-time
        co-location counters, and the Command funnel's ledger."""
        now = metrics.elastic_counts()
        d = {k: int(now.get(k, 0) - self._eg_mark.get(k, 0))
             for k in set(now) | set(self._eg_mark)}
        shrinks = {k.split("/", 1)[1]: v for k, v in d.items()
                   if k.startswith("shrink/") and v}
        placed = self.colocated_gangs + self.spread_gangs
        return {
            "enabled": self.elastic_gangs,
            "topology_weight": self.topology_weight,
            "grows": d.get("grows", 0),
            "shrinks": dict(sorted(shrinks.items())),
            "below_min_evictions": d.get("below_min", 0),
            "elastic_continues": self._elastic_continues,
            "colocated_gangs": self.colocated_gangs,
            "spread_gangs": self.spread_gangs,
            "colocation_rate": round(self.colocated_gangs / placed, 4)
            if placed else 0.0,
            "commands": self._command_funnel.stats()
            if self._command_funnel is not None else {},
        }

    def fast_admit_stats(self) -> Dict[str, int]:
        now = metrics.fast_admit_counts()
        return {k: int(now.get(k, 0) - self._fa_mark.get(k, 0))
                for k in ("gangs", "binds")}

    def mesh_stats(self) -> Dict[str, object]:
        """The report's deterministic mesh section (seeded injector +
        virtual-clock windows ⇒ byte-reproducible): faults injected per
        kind and device, heal/quarantine/readmission deltas
        (process-global counters marked at construction), the per-rung
        cycle tally, and the never-CPU witness (rung-3 cycles — expected
        0 whenever any device survives). run() snapshots this BEFORE it
        hands DEVICE_HEALTH back to wall time, so the section reflects
        the run, not the post-run reset."""
        if self._mesh_section is not None:
            return self._mesh_section
        from ..device_health import DEVICE_HEALTH
        now = metrics.mesh_counts()
        d = {k: now.get(k, 0) - self._mesh_mark.get(k, 0)
             for k in set(now) | set(self._mesh_mark)}
        heals = {k.split("/", 1)[1]: int(v) for k, v in d.items()
                 if k.startswith("heals/") and v}
        quars = {k.split("/", 1)[1]: int(v) for k, v in d.items()
                 if k.startswith("quarantines/") and v}
        inj: Dict[str, int] = {}
        devices_hit: List[int] = []
        if self._mesh_injector is not None:
            for _, kind, dev in self._mesh_injector.injected:
                inj[kind] = inj.get(kind, 0) + 1
                if dev not in devices_hit:
                    devices_hit.append(dev)
        detail = DEVICE_HEALTH.detail()
        return {
            "fault_rate": self.mesh_fault_rate,
            "injected": dict(sorted(inj.items())),
            "devices_faulted": sorted(devices_hit),
            "heals": dict(sorted(heals.items())),
            "quarantines": dict(sorted(quars.items())),
            "readmissions": int(d.get("readmissions", 0)),
            "rung_cycles": {str(k): v for k, v in
                            sorted(self.rung_cycles.items())},
            "cpu_fallback_cycles": int(self.rung_cycles.get(3, 0)),
            "devices_healthy_final": detail["devices_healthy"],
            "devices_quarantined_final": detail["devices_quarantined"],
        }

    @property
    def ack_chaos(self) -> bool:
        return self._ack_injector is not None

    def feedback_stats(self) -> Dict[str, object]:
        """The report's deterministic feedback-plane section (seeded
        chaos + virtual clock ⇒ byte-reproducible): faults injected on
        the ack wire, normalizer verdicts, in-flight ledger resolutions,
        and the zero-stuck witnesses (open entries / pending acks at
        run end)."""
        faults: Dict[str, int] = {}
        injectors = ([self._ack_injector] if not self.store_wired
                     else self._store_ack_injectors)
        for inj in injectors:
            if inj is None:
                continue
            for kind, n in inj.injected.items():
                faults[kind] = faults.get(kind, 0) + n
        acks: Dict[str, int] = {}
        resolved: Dict[str, int] = {}
        open_entries = 0
        pending_watch = 0
        for cache in self.caches:
            for (kind, verdict), n in cache.feedback.counts.items():
                key = f"{kind}/{verdict}"
                acks[key] = acks.get(key, 0) + n
            for how, n in cache.inflight.resolved.items():
                resolved[how] = resolved.get(how, 0) + n
            open_entries += cache.inflight.open_count()
            pending_watch += cache.feedback.pending()
        return {
            "fault_rate": self.ack_fault_rate,
            "faults": dict(sorted(faults.items())),
            "acks": dict(sorted(acks.items())),
            "inflight_resolved": dict(sorted(resolved.items())),
            "inflight_open": open_entries,
            "wire_pending": self._ack_wire.pending() + pending_watch,
            "watchdog_fired": sum(
                resolved.get(k, 0)
                for k in ("repaired", "rolled_back", "reissued")),
        }

    def lifecycle_stats(self) -> Dict[str, object]:
        """The report's ``latency`` section (--lifecycle only): per queue
        class, percentiles of every timeline-derived latency span —
        ttfb_s must agree with ``queueing_delay_s`` and jct_s with
        ``jct_s`` above (the oracle-parity contract the lifecycle tests
        assert), because both planes sample the SAME virtual instants."""
        from ..obs.lifecycle import latency_classes
        classes = latency_classes(self._timeline)
        stats = self._timeline.stats()
        return {
            "classes": {
                cls: {kind: report_mod.percentiles(vals)
                      for kind, vals in sorted(kinds.items())}
                for cls, kinds in sorted(classes.items())},
            "timeline": {
                "jobs": stats["jobs"],
                "events": stats["events"],
                "lru_evicted": stats["evicted"],
                "duplicates_dropped": stats["duplicates_dropped"],
            },
        }

    def slo_status(self) -> List[dict]:
        """End-of-run SLO evaluation (--lifecycle only), published to the
        metrics plane as it goes so /healthz?detail and the gauges agree
        with the report."""
        return self._slo_engine.publish(self._timeline,
                                        now=self.clock.time())

    def run(self) -> dict:
        """Run the trace to completion (or stall/max_cycles); returns the
        report dict (sim/report.py)."""
        wall0 = time.perf_counter()
        mark = metrics.durations_mark()
        stall = 0
        last_sig = None
        while self.cycles < self.max_cycles:
            now = self.clock.time()
            if self.overload:
                # shed clients whose retry_after expired re-POST, and
                # the seeded OverloadInjector may land a flash crowd —
                # both through the same admission gate as the trace
                self._drain_retries(now)
                self._inject_bursts(now)
            self._apply_trace_until(now)
            self._fire_completions_until(now)
            if self.store_wired:
                # client submissions that failed at the store boundary
                # retry here; the seeded torn-watch drill fires at its
                # scheduled cycles (the schedulers' epilogue upkeep must
                # then resume/relist the streams)
                self._drain_store_pending()
                while self._tear_cycles \
                        and self._tear_cycles[0] <= self.cycles:
                    self._tear_cycles.pop(0)
                    self.torn_watch_events += len(
                        self.world.tear_streams(1, self._tear_rng))
            if self.fast_admit_mode and not self.federated \
                    and not self.replicas:
                # event-driven fast path: arrivals just applied bind NOW
                # (sub-cycle time-to-first-bind) through the journaled
                # funnel; the feedback pass stamps their first_bind at
                # the CURRENT virtual time, before the full cycle runs
                if self.sched.fast_admit():
                    self._feedback(now)
            if self.federated:
                self._federated_cycle(now)
            elif self.replicas:
                self._ha_cycle(now)
            else:
                kill_mode = None
                if self.cycles in self.kill_cycles:
                    kill_mode = self._arm_kill()
                t0 = time.perf_counter()
                try:
                    errors = self.sched.run_once()
                except SimKill:
                    errors = []
                    self._crash_restart(kill_mode)
                else:
                    if kill_mode == "post_cycle":
                        # clean-boundary death: nothing mid-flight, but all
                        # volatile state (queued retries!) dies with the
                        # process
                        self._crash_restart("post_cycle")
                    elif kill_mode is not None:
                        # the armed kill point never fired this cycle (too
                        # few side effects) — the "crash" degenerates to a
                        # restart at the boundary, which is still a real
                        # restart (and the crash window is empty, so no
                        # oracle is needed)
                        self._crash_restart("post_cycle")
                self.pipeline_e2e_ms.append(
                    (time.perf_counter() - t0) * 1e3)
                for name, _ in errors:
                    self.action_failures.append((self.cycles, name))
                self._feedback(now)
            # decision-plane samples: in federated mode the planes live
            # in DISJOINT partition caches, so utilization/fairness
            # aggregate across them; single/HA read the (converged) view
            sample = self.caches if self.federated else [self._view()]
            if self.elastic:
                self._sample_queue_depth()
            self.util_cpu.append(report_mod.cpu_utilization_all(sample))
            self.util_mem.append(report_mod.mem_utilization_all(sample))
            self.drf_gap.append(report_mod.drf_fairness_gap_all(sample))
            if self._admission is not None:
                # feed the drain-throughput EWMA behind the front
                # door's retry_after hints (virtual counts — the hint
                # stream is deterministic)
                self._admission.observe_drain(self._drained_tasks)
                self._drained_tasks = 0
            if self.mesh_chaos:
                # per-rung cycle tally: the gauge holds the rung the
                # allocate gate picked this cycle (0 full .. 3 CPU) —
                # a pure function of the seeded fault stream on the
                # virtual clock, so the tally is deterministic
                rung = int(metrics.mesh_counts().get("rung", 0))
                self.rung_cycles[rung] = self.rung_cycles.get(rung, 0) + 1
            self.cycles += 1
            self.clock.sleep(self.period)
            if self._done():
                break
            sig = self._progress_signature()
            stall = stall + 1 if sig == last_sig else 0
            last_sig = sig
            if stall >= self.stall_limit:
                break                # wedged backlog: report what's left
        wall_s = time.perf_counter() - wall0
        # hand the (global) device-health state machine back to wall time
        # so post-sim code in the same process isn't stuck on a frozen
        # virtual clock; the mesh section must be snapshotted FIRST (the
        # reset clears the lattice the section reads)
        from ..device_health import (DEFAULT_COOLDOWN_S,
                                     DEFAULT_MAX_COOLDOWN_S, DEVICE_HEALTH)
        if self.mesh_chaos:
            self._mesh_section = self.mesh_stats()
            from ..actions import allocate as _alloc_mod
            if _alloc_mod.DEVICE_FAULT_HOOK is self._mesh_injector:
                _alloc_mod.DEVICE_FAULT_HOOK = None
            DEVICE_HEALTH.cooldown_s = DEFAULT_COOLDOWN_S
            DEVICE_HEALTH.max_cooldown_s = DEFAULT_MAX_COOLDOWN_S
        DEVICE_HEALTH.reset(time_fn=time.monotonic)
        # runs longer than the bounded metrics ring lose their oldest
        # per-action samples — flag the affected series so the report's
        # percentiles aren't read as whole-run stats
        since = metrics.durations_since(mark)
        end = metrics.durations_mark()
        truncated = sorted(
            "/".join(k) for k, vals in since.items()
            if end.get(k, 0) - mark.get(k, 0) > len(vals))
        return report_mod.build_report(
            self, actions_ms=since, wall_s=wall_s,
            actions_truncated=truncated)
