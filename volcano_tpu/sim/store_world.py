"""Store-wired sim mode (docs/simulation.md --store-wired): the cluster
truth lives in a real :class:`ObjectStore` and every scheduler↔cluster
interaction crosses the hostile store boundary of store_transport.py —
per-verb seeded faults (latency on virtual time, transients the retry
funnel must absorb, 409s), torn watch streams the resumable informers
must recover, and the store-backed federation CR when combined with
``--federated``.

Topology per scheduler (partition): its OWN FaultyStoreTransport (own
seeded injector — two apiserver connections don't fail in lockstep)
under a RetryingStoreTransport pinned to the virtual clock and a seeded
jitter RNG; the cache is wired through resumable watches
(cache/watches.py), so the scheduler epilogue's upkeep step IS what
heals torn streams mid-soak.

Harness-side operations (the kubelet/job-controller analogues the sim
performs: completing gangs, recreating evicted pods, node death) go to
the RAW store — they model cluster components, not the scheduler's
connection, and the soak's accounting oracle must not depend on the
harness outrunning its own chaos. Client submissions DO ride a faulted
transport and re-queue on failure (a client retrying its POST).

The bind/evict determinism witness: a shared recording wrapper between
the (chaos/kill) wrappers and the per-scheduler StoreBinder — exactly
the executions that reached the store, in execution order, which is
also the crash-window oracle the journal reconciler consumes.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from ..apis.objects import (ObjectMeta, Pod, PodGroupCR, PodGroupSpec,
                            PodTemplate, PriorityClass, QueueCR,
                            QueueSpecCR)
from ..api import Resource
from ..cache import SchedulerCache
from ..cache.executors import (Binder, Evictor, StoreBinder, StoreEvictor,
                               StoreStatusUpdater)
from ..cache.store_wiring import GROUP_NAME_ANNOTATION, wire_cache_to_store
from ..chaos import StoreFaultInjector
from ..store import ObjectStore
from ..store_transport import FaultyStoreTransport, RetryingStoreTransport


class SharedWitness:
    """Duck-typed stand-in for SequenceBinder/SequenceEvictor on the
    runner: the shared ``sequence`` every partition's recording wrapper
    appends to (and ``binds`` for the binder half)."""

    def __init__(self):
        self.sequence: List = []
        self.binds: Dict[str, str] = {}


class RecordingBinder(Binder):
    """Appends to the shared witness AFTER the inner executor succeeded
    — the bind reached the store (SequenceBinder semantics over a real,
    failable executor)."""

    def __init__(self, inner: Binder, witness: SharedWitness):
        self.inner = inner
        self.witness = witness

    def bind(self, task, hostname: str) -> None:
        self.inner.bind(task, hostname)
        self.witness.sequence.append((task.uid, hostname))
        self.witness.binds[task.key()] = hostname


class RecordingEvictor(Evictor):
    def __init__(self, inner: Evictor, witness: SharedWitness):
        self.inner = inner
        self.witness = witness

    def evict(self, task, reason: str) -> None:
        self.inner.evict(task, reason)
        self.witness.sequence.append(task.uid)


class StoreWorld:
    """The store-wired sim's cluster: one raw ObjectStore (truth), one
    hostile transport per scheduler, the shared bind/evict witness, and
    the pod blueprints the harness recreates evicted pods from."""

    def __init__(self, clock, fault_rate: float = 0.0, fault_seed: int = 0,
                 latency_s: float = 0.05, n_schedulers: int = 1,
                 retry_rng_seed: int = 0, period: float = 1.0):
        self.clock = clock
        self.store = ObjectStore()
        self.fault_rate = fault_rate
        self.fault_seed = fault_seed
        self.latency_s = latency_s
        self.retry_rng_seed = retry_rng_seed
        self.period = period
        self.bind_witness = SharedWitness()
        self.evict_witness = SharedWitness()
        self.injectors: List[StoreFaultInjector] = []
        self.faulties: List[FaultyStoreTransport] = []
        self.transports: List[RetryingStoreTransport] = []
        for _ in range(max(n_schedulers, 1)):
            self.add_scheduler()
        # pod uid -> blueprint for the controller-recreate analogue
        self._blueprints: Dict[str, dict] = {}
        self._known_prio: set = set()
        # completed job names: a still-retrying submission thunk must
        # not resurrect a gang that already finished
        self._completed: set = set()

    # -- per-scheduler wiring -------------------------------------------------

    def add_scheduler(self) -> int:
        """Mint one more scheduler's hostile store chain (its own seeded
        injector under its own retry funnel) and return its transport
        index. Seeds derive from the index exactly as at construction,
        so a partition SPAWNED mid-run (sim --elastic) replays the same
        fault stream a same-index partition built up front would — the
        elastic soak stays byte-deterministic."""
        i = len(self.transports)
        inj = StoreFaultInjector(
            failure_rate=self.fault_rate, seed=self.fault_seed * 7919 + i,
            latency_s=self.latency_s, sleep_fn=self.clock.sleep)
        faulty = FaultyStoreTransport(self.store, inj)
        transport = RetryingStoreTransport(
            faulty, sleep_fn=self.clock.sleep, time_fn=self.clock.time,
            cycle_budget_s=2.0 * self.period,
            rng=random.Random(self.retry_rng_seed * 31 + i))
        self.injectors.append(inj)
        self.faulties.append(faulty)
        self.transports.append(transport)
        return i

    def build_cache(self, ix: int = 0,
                    binder_wrap: Optional[Callable] = None,
                    evictor_wrap: Optional[Callable] = None,
                    journal=None,
                    event_filter: Optional[Callable] = None,
                    fence: Optional[Callable] = None,
                    ) -> Tuple[SchedulerCache, Binder, Evictor]:
        """One scheduler's cache over its own hostile transport:
        executors ride retry funnel → faulty transport → store, wrapped
        (inside out) by the shared witness recorder, the optional chaos
        wraps, and the optional fencing gate (``fence(binder, evictor)``
        applied OUTERMOST, matching the HA/federated chains). Returns
        ``(cache, kill_binder_slot, kill_evictor_slot)`` — the chain
        BEFORE fencing so kill wrappers can be interposed by the
        caller."""
        transport = self.transports[ix]
        binder: Binder = RecordingBinder(StoreBinder(transport),
                                         self.bind_witness)
        evictor: Evictor = RecordingEvictor(StoreEvictor(transport),
                                            self.evict_witness)
        if binder_wrap is not None:
            binder = binder_wrap(binder)
        if evictor_wrap is not None:
            evictor = evictor_wrap(evictor)
        cache = SchedulerCache(
            binder=binder, evictor=evictor,
            status_updater=StoreStatusUpdater(transport),
            default_queue=None, journal=journal)
        cache.resync_queue.time_fn = self.clock.time
        cache.time_fn = self.clock.time
        wire_cache_to_store(transport, cache=cache,
                            event_filter=event_filter)
        return cache, binder, evictor

    # -- seeded whole-stream tears -------------------------------------------

    def tear_streams(self, n: int, rng: random.Random) -> List[str]:
        """Tear ``n`` live watch streams chosen across every scheduler's
        transport — the scheduled torn-watch drill; the schedulers'
        epilogue upkeep (or the federation sync hook) must recover them."""
        torn: List[str] = []
        for _ in range(n):
            live = [(f, s) for f in self.faulties
                    for s in f.streams if not s.torn]
            if not live:
                break
            f, s = live[rng.randrange(len(live))]
            s.tear()
            torn.append(s.kind)
            from .. import metrics
            metrics.register_store_fault("watch", "torn")
        return torn

    def faults_detail(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for inj in self.injectors:
            for kind, n in inj.injected.items():
                out[kind] = out.get(kind, 0) + n
        return out

    def retry_detail(self) -> Dict[str, int]:
        return {
            "retries": sum(t.retries for t in self.transports),
            "exhausted": sum(t.exhausted for t in self.transports),
        }

    # -- client-side submission (rides the faulted transport) -----------------

    def submit_job(self, ix: int, t: float, d: dict) -> Callable[[], None]:
        """Build the idempotent submission thunk for one job_arrival
        trace event: PriorityClass (on demand) + PodGroup + pod batch,
        resumable — a thunk that raised is re-run next cycle and only
        creates what is still missing (the client retrying its POSTs).
        Returns the thunk; the caller runs/queues it."""
        name, ns = d["name"], "default"
        transport = self.transports[min(ix, len(self.transports) - 1)]
        pc_name = f"prio-{d['priority']}" if d["priority"] else ""
        scalars = {"nvidia.com/gpu": float(d["gpus"])} if d["gpus"] else None
        pods = []
        for i in range(d["tasks"]):
            uid = f"{name}-{i}"
            pod = Pod(metadata=ObjectMeta(
                name=uid, namespace=ns, uid=uid,
                annotations={GROUP_NAME_ANNOTATION: name},
                creation_timestamp=t + i * 1e-6),
                template=PodTemplate(
                    resources=Resource(d["cpu_milli"], d["mem"], scalars),
                    priority=d["priority"]))
            pods.append(pod)
            self._blueprints[uid] = {
                "name": uid, "namespace": ns, "group": name,
                "creation_timestamp": t + i * 1e-6,
                "cpu_milli": d["cpu_milli"], "mem": d["mem"],
                "gpus": d["gpus"], "priority": d["priority"]}

        def thunk() -> None:
            if name in self._completed:
                return
            if pc_name and pc_name not in self._known_prio:
                if self.store.get("PriorityClass", ns, pc_name) is None:
                    transport.create(PriorityClass(
                        metadata=ObjectMeta(name=pc_name, namespace=ns),
                        value=d["priority"]))
                self._known_prio.add(pc_name)
            if self.store.get("PodGroup", ns, name) is None:
                transport.create(PodGroupCR(
                    metadata=ObjectMeta(name=name, namespace=ns,
                                        creation_timestamp=t),
                    spec=PodGroupSpec(min_member=d["min_available"],
                                      queue=d["queue"],
                                      priority_class_name=pc_name)))
            missing = [p for p in pods
                       if self.store.get("Pod", ns,
                                         p.metadata.name) is None]
            if missing:
                transport.create_batch(missing)

        return thunk

    def submit_queue(self, ix: int, d: dict) -> Callable[[], None]:
        name = d["name"]
        transport = self.transports[min(ix, len(self.transports) - 1)]

        def thunk() -> None:
            if self.store.get("Queue", "default", name) is None:
                transport.create(QueueCR(
                    metadata=ObjectMeta(name=name, namespace="default"),
                    spec=QueueSpecCR(weight=d["weight"])))

        return thunk

    # -- kubelet / job-controller analogues (raw store) -----------------------

    def recreate_pod(self, uid: str) -> bool:
        """Controller-recreate after an eviction/node death: a FRESH pod
        from the blueprint (same uid/name/timestamps — the recreated pod
        is the same logical member, as the direct-mode sim models)."""
        bp = self._blueprints.get(uid)
        if bp is None:
            return False
        if self.store.get("Pod", bp["namespace"], bp["name"]) is not None:
            return False
        scalars = {"nvidia.com/gpu": float(bp["gpus"])} if bp["gpus"] \
            else None
        self.store.create(Pod(metadata=ObjectMeta(
            name=bp["name"], namespace=bp["namespace"], uid=uid,
            annotations={GROUP_NAME_ANNOTATION: bp["group"]},
            creation_timestamp=bp["creation_timestamp"]),
            template=PodTemplate(
                resources=Resource(bp["cpu_milli"], bp["mem"], scalars),
                priority=bp["priority"])))
        return True

    def delete_pod(self, uid: str) -> None:
        bp = self._blueprints.get(uid)
        if bp is not None:
            self.store.delete("Pod", bp["namespace"], bp["name"])

    def complete_job(self, jid: str, task_uids: List[str]) -> None:
        """Gang completion: the pods and the PodGroup leave the cluster
        (job controller cleanup); caches follow through their watches."""
        ns, name = jid.split("/", 1)
        self._completed.add(name)
        for uid in task_uids:
            self.delete_pod(uid)
            self._blueprints.pop(uid, None)
        self.store.delete("PodGroup", ns, name)

    def pods_on_node(self, node_name: str) -> List[str]:
        return sorted(
            p.metadata.uid for p in self.store.list("Pod")
            if p.status.node_name == node_name
            and p.status.phase == "Running")
