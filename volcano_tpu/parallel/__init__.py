from ..ops.unified import (NODE_AXIS, make_mesh, place_blocks_unified,
                           place_scan_unified)
from .mesh import place_blocks_sharded

__all__ = ["NODE_AXIS", "make_mesh", "place_blocks_sharded",
           "place_blocks_unified", "place_scan_unified"]
