from .mesh import NODE_AXIS, make_mesh, place_blocks_sharded

__all__ = ["NODE_AXIS", "make_mesh", "place_blocks_sharded"]
