"""Multi-chip scaling: shard the placement solve over a device mesh.

SURVEY.md §5.7: the reference's "long axis" analogue is the node axis (2k →
tens of k) and the pending-task axis (10k+). This module shards the
block-greedy solver (ops/auction.py) over the NODE axis with ``shard_map`` —
each device owns a node shard and scores every task chunk against it; the
global best node per task is resolved with one ``all_gather`` of per-shard
(score, index) maxima per chunk (the structural cousin of a ring-attention
step: local compute + a small collective across the ring). Gang admission is
a ``psum`` of per-job placement counts.

All collectives ride ICI inside one jit program; nothing touches the host
between chunks.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dense import EPS
from ..ops.place import NO_NODE, JobMeta, NodeState
from ..ops.scores import ScoreWeights, combined_dynamic_score

NODE_AXIS = "nodes"


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def _sharded_chunk_step(axis: str):
    """One chunk over node-sharded state. Runs inside shard_map: all array
    args are the per-device shards."""

    def step(carry, chunk, *, allocatable, max_tasks, weights, shard_offset):
        nodes: NodeState = carry
        req, valid = chunk                                  # [C,R] replicated
        C, R = req.shape
        Nl = nodes.idle.shape[0]                            # local shard size

        pods_ok = nodes.ntasks < max_tasks
        fit = (jnp.all(req[:, None, :] < nodes.idle[None] + EPS, axis=-1)
               & pods_ok[None])                              # [C,Nl]
        score = combined_dynamic_score(req, nodes.used, allocatable, weights)
        masked = jnp.where(fit, score, -jnp.inf)
        local_best = jnp.argmax(masked, axis=-1)             # [C]
        local_score = masked[jnp.arange(C), local_best]      # [C]

        # Resolve the global winner per task with one gather across shards.
        all_scores = jax.lax.all_gather(local_score, axis)   # [D,C]
        my_shard = jax.lax.axis_index(axis)
        winner_shard = jnp.argmax(all_scores, axis=0)        # [C]
        has_node = jnp.max(all_scores, axis=0) > -jnp.inf
        mine = (winner_shard == my_shard) & has_node & valid # [C]

        # Local contention resolution for tasks won by this shard
        # (same two-wave scheme as ops/auction.py).
        choice = local_best
        onehot = jax.nn.one_hot(choice, Nl, dtype=req.dtype) * mine[:, None]

        def contention(accept_mask):
            live = onehot * accept_mask[:, None]
            demand = live[:, :, None] * req[:, None, :]
            cum = jnp.cumsum(demand, axis=0) - demand
            room = jnp.all(
                req[:, None, :] + cum[jnp.arange(C), choice][:, None, :]
                < nodes.idle[choice][:, None, :] + EPS, axis=-1)[:, 0]
            cum_count = jnp.cumsum(live, axis=0) - live
            pods_room = (nodes.ntasks[choice]
                         + cum_count[jnp.arange(C), choice] < max_tasks[choice])
            return mine & room & pods_room

        accept = contention(jnp.ones(C, dtype=bool))
        accept = accept | contention(accept)
        accept = contention(accept)

        placed = onehot * accept[:, None]
        delta = jnp.einsum("cn,cr->nr", placed, req)
        nodes = NodeState(
            idle=nodes.idle - delta,
            future_idle=nodes.future_idle - delta,
            used=nodes.used + delta,
            ntasks=nodes.ntasks + jnp.sum(placed, axis=0).astype(jnp.int32))

        # global node index of the accepted pick; psum merges shards (every
        # non-winning shard contributes 0).
        local_pick = jnp.where(accept, shard_offset + choice + 1, 0)
        global_pick = jax.lax.psum(local_pick, axis) - 1     # NO_NODE == -1
        return nodes, global_pick.astype(jnp.int32)

    return step


def place_blocks_sharded(mesh: Mesh, nodes: NodeState, req: jnp.ndarray,
                         valid: jnp.ndarray, job_ix: jnp.ndarray,
                         jobs: JobMeta, weights: ScoreWeights,
                         allocatable: jnp.ndarray, max_tasks: jnp.ndarray,
                         chunk: int = 256, sweeps: int = 2,
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, NodeState]:
    """Node-sharded block-greedy placement over ``mesh``.

    nodes/allocatable/max_tasks are sharded on the node axis; tasks
    (req/valid/job_ix) and JobMeta are replicated. Returns
    (task_node i32[T] global indices, job_ready bool[J], sharded NodeState).
    N must be divisible by the mesh size (pad with zero-capacity nodes).
    """
    D = mesh.devices.size
    N = allocatable.shape[0]
    assert N % D == 0, f"node count {N} not divisible by mesh size {D}"
    T = req.shape[0]
    pad = (-T) % chunk
    if pad:
        req = jnp.pad(req, ((0, pad), (0, 0)))
        valid = jnp.pad(valid, (0, pad))
        job_ix = jnp.pad(job_ix, (0, pad))
    Tp = T + pad
    n_chunks = Tp // chunk
    J = jobs.min_available.shape[0]

    node_sharded = P(NODE_AXIS)
    repl = P()

    @partial(jax.shard_map, mesh=mesh,
             in_specs=(NodeState(*(node_sharded,) * 4), node_sharded,
                       node_sharded, repl, repl, repl),
             out_specs=(repl, repl, NodeState(*(node_sharded,) * 4)),
             check_vma=False)
    def solve(nodes, allocatable, max_tasks, req, valid, job_ix):
        Nl = allocatable.shape[0]
        shard_offset = jax.lax.axis_index(NODE_AXIS) * Nl
        step = partial(_sharded_chunk_step(NODE_AXIS),
                       allocatable=allocatable, max_tasks=max_tasks,
                       weights=weights, shard_offset=shard_offset)

        assign0 = jnp.full(Tp, NO_NODE, dtype=jnp.int32)

        def place_pass(carry, _):
            nodes, assign, job_dead = carry
            todo = (assign == NO_NODE) & valid & ~job_dead[job_ix]
            xs = (req.reshape(n_chunks, chunk, -1),
                  todo.reshape(n_chunks, chunk))
            nodes, out = jax.lax.scan(step, nodes, xs)
            assign = jnp.where(assign == NO_NODE, out.reshape(Tp), assign)
            return (nodes, assign, job_dead), None

        def sweep(carry, _):
            (nodes, assign, job_dead), _ = jax.lax.scan(
                place_pass, carry, jnp.arange(2))

            placed = assign != NO_NODE
            counts = jax.ops.segment_sum(placed.astype(jnp.int32), job_ix,
                                         num_segments=J)
            ready = counts + jobs.base_ready >= jobs.min_available
            drop = placed & ~ready[job_ix]
            # free dropped demand on the owning shard
            local = (assign >= shard_offset) & (assign < shard_offset + Nl) & drop
            drop_hot = (jax.nn.one_hot(
                jnp.where(local, assign - shard_offset, 0), Nl,
                dtype=req.dtype) * local[:, None])
            freed = jnp.einsum("tn,tr->nr", drop_hot, req)
            nodes = NodeState(
                idle=nodes.idle + freed,
                future_idle=nodes.future_idle + freed,
                used=nodes.used - freed,
                ntasks=nodes.ntasks - jnp.sum(drop_hot, axis=0).astype(jnp.int32))
            assign = jnp.where(drop, NO_NODE, assign)
            job_dead = job_dead | (~ready & (counts > 0))
            return (nodes, assign, job_dead), ready

        (nodes, assign, _), readies = jax.lax.scan(
            sweep, (nodes, assign0, jnp.zeros(J, dtype=bool)),
            jnp.arange(sweeps))
        return assign, readies[-1], nodes

    assign, ready, nodes = solve(nodes, allocatable, max_tasks, req, valid,
                                 job_ix)
    return assign[:T], ready, nodes
