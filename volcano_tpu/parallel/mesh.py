"""Multi-chip scaling — compatibility shim.

The node-sharded solver that used to live here was unified with the
single-device blocks/scan kernels into ops/unified.py: ONE
shard_map-partitioned solver (nodes axis sharded, jobs axis replicated)
whose packed single-fetch wire layout and mesh-size-invariant decisions
serve every allocate engine. This module re-exports the mesh plumbing
(`NODE_AXIS`, `make_mesh`, `shard_map_compat`) for its existing importers
(ops/evict.py, actions/evict_tpu.py) plus an unpacking
``place_blocks_sharded`` wrapper for the dryrun/test callers of the old
5-tuple contract; new code should import from volcano_tpu.ops.unified
directly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from ..ops.place import NodeState
from ..ops.unified import (  # noqa: F401
    NODE_AXIS, make_mesh, padded_task_len, place_blocks_unified,
    place_scan_unified, shard_map_compat)

__all__ = ["NODE_AXIS", "make_mesh", "place_blocks_sharded",
           "place_blocks_unified", "place_scan_unified", "shard_map_compat"]


def place_blocks_sharded(mesh, nodes: NodeState, req, valid, job_ix, jobs,
                         weights, allocatable, max_tasks, chunk: int = 256,
                         sweeps: int = 3, passes: int = 3,
                         masked_static: Optional[jnp.ndarray] = None,
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                    jnp.ndarray, NodeState]:
    """The pre-unification 5-tuple surface, now a slicing view over the
    unified solver's packed result: (task_node i32[T], pipelined bool[T],
    job_ready bool[J], job_kept bool[J], nodes). The slices stay on
    device — no fetch happens here."""
    T = req.shape[0]
    J = jobs.min_available.shape[0]
    packed, out_nodes = place_blocks_unified(
        mesh, nodes, req, valid, job_ix, jobs, weights, allocatable,
        max_tasks, chunk=chunk, sweeps=sweeps, passes=passes,
        masked_static=masked_static)
    Tp = padded_task_len(T, chunk)
    return (packed[:T], packed[Tp:Tp + T].astype(bool),
            packed[2 * Tp:2 * Tp + J].astype(bool),
            packed[2 * Tp + J:2 * Tp + 2 * J].astype(bool), out_nodes)
