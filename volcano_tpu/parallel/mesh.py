"""Multi-chip scaling: shard the placement solve over a device mesh.

SURVEY.md §5.7: the reference's "long axis" analogue is the node axis (2k →
tens of k) and the pending-task axis (10k+). This module shards the
block-greedy solver (ops/auction.py) over the NODE axis with ``shard_map`` —
each device owns a node shard and scores every task chunk against it; the
global best node per task is resolved with one ``all_gather`` of per-shard
(score, index) maxima per chunk (the structural cousin of a ring-attention
step: local compute + a small collective across the ring). Gang admission is
a ``psum`` of per-job placement counts.

All collectives ride ICI inside one jit program; nothing touches the host
between chunks. The compiled solver is cached per (mesh, chunk, sweeps) with
job metadata and score weights as runtime arguments, so a scheduler calling
it every cycle pays one compile per shape bucket, not per cycle; the
(assign, pipelined, ready, kept) results come back in ONE packed
device->host fetch (tunnel RTT dominates payload size on remote TPU
backends).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.auction import K_CAND
from ..ops.dense import EPS
from ..ops.pallas_place import NEG, NEG_TEST
from ..ops.place import NO_NODE, JobMeta, NodeState
from ..ops.scores import ScoreWeights, combined_dynamic_score

NODE_AXIS = "nodes"


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def shard_map_compat(fn, *, mesh, in_specs, out_specs):
    """shard_map across jax releases: ``jax.shard_map(..., check_vma=)`` on
    new jax, ``jax.experimental.shard_map.shard_map(..., check_rep=)``
    before the promotion. Without this shim the whole multi-chip engine
    family dies with an AttributeError on one side of the move — a
    toolchain-version fault, not a scheduling fault, so it is absorbed
    here instead of crashing the cycle (docs/robustness.md)."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    # the replication/VMA check must stay OFF (the solvers' out_specs are
    # not provably replicated), under whichever keyword this jax spells
    # it. Probe the signature rather than catching TypeError — a genuine
    # TypeError from shard_map's own argument validation must surface as
    # itself, not as a bogus incompatibility retry.
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        kw = {"check_vma": False}
    elif "check_rep" in params:
        kw = {"check_rep": False}
    else:
        raise TypeError(
            "installed jax's shard_map accepts neither check_vma nor "
            "check_rep; cannot disable the replication check the sharded "
            "solvers require")
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def _sharded_chunk_step(axis: str, has_ms: bool):
    """One chunk over node-sharded state. Runs inside shard_map: all array
    args are the per-device shards.

    Mirrors ops/auction._chunk_step's top-K bidding: every shard offers its
    local top-K candidates, one all_gather merges them into a global top-K
    per task, then K contention rounds let a task rejected at its r-th
    choice fall to its (r+1)-th. Contention for a node is resolved on the
    shard that owns it; one psum per round merges accept verdicts."""

    def step(carry, chunk, *, allocatable, max_tasks, weights, shard_offset):
        nodes: NodeState = carry
        if has_ms:
            req, valid, ms = chunk          # req/valid replicated, ms sharded
        else:
            req, valid = chunk
            ms = None
        C, R = req.shape
        Nl = nodes.idle.shape[0]                            # local shard size
        K = min(K_CAND, Nl)

        pods_ok = nodes.ntasks < max_tasks
        # bid eligibility is FutureIdle-based (allocate.go:232-256): a task
        # that does not fit Idle may still pipeline onto releasing capacity;
        # the alloc-vs-pipeline split is resolved per accepted task below
        fit = (jnp.all(req[:, None, :] < nodes.future_idle[None] + EPS,
                       axis=-1) & pods_ok[None])              # [C,Nl]
        score = combined_dynamic_score(req, nodes.used, allocatable, weights)
        if ms is not None:
            fit = fit & (ms > NEG_TEST)
            score = score + ms
        masked = jnp.where(fit, score, -jnp.inf)
        lscore, lidx = jax.lax.top_k(masked, K)              # [C,K] local
        gidx = lidx + shard_offset

        # merge every shard's candidates into a global per-task top-K:
        # one gather of [D,C,K] scores + ids across the mesh.
        all_s = jax.lax.all_gather(lscore, axis)             # [D,C,K]
        all_i = jax.lax.all_gather(gidx, axis)
        D = all_s.shape[0]
        flat_s = jnp.moveaxis(all_s, 0, 1).reshape(C, D * K)
        flat_i = jnp.moveaxis(all_i, 0, 1).reshape(C, D * K)
        cand_score, pos = jax.lax.top_k(flat_s, K)           # [C,K] global
        cand = jnp.take_along_axis(flat_i, pos, axis=1)

        lower = jnp.arange(C)[:, None] > jnp.arange(C)[None, :]

        def round_body(_, st):
            accept, choice_g, slot = st
            bid_g = jnp.take_along_axis(cand, slot[:, None], 1)[:, 0]
            bscore = jnp.take_along_axis(cand_score, slot[:, None], 1)[:, 0]
            bidding = ~accept & valid & (bscore > -jnp.inf)
            local = (bid_g >= shard_offset) & (bid_g < shard_offset + Nl)
            bid_l = jnp.clip(bid_g - shard_offset, 0, Nl - 1)
            bidding_l = bidding & local

            # claimed capacity on this shard from earlier-round accepts
            choice_l = jnp.clip(choice_g - shard_offset, 0, Nl - 1)
            acc_l = (accept & (choice_g >= shard_offset)
                     & (choice_g < shard_offset + Nl))
            claimed_hot = (jax.nn.one_hot(choice_l, Nl, dtype=req.dtype)
                           * acc_l[:, None])
            claimed = jnp.einsum("cn,cr->nr", claimed_hot, req)
            claimed_cnt = jnp.sum(claimed_hot, axis=0)
            avail_bid = nodes.future_idle[bid_l] - claimed[bid_l]
            base_cnt = nodes.ntasks[bid_l] + claimed_cnt[bid_l]
            maxt_bid = max_tasks[bid_l]

            same = (bid_l[:, None] == bid_l[None, :]) & lower

            def wave(mask):
                live = (mask & bidding_l).astype(req.dtype)
                m = same * live[None, :]
                cum = m.astype(req.dtype) @ req
                room = jnp.all(req + cum < avail_bid + EPS, axis=-1)
                cnt = jnp.sum(m, axis=1)
                return bidding_l & room & (base_cnt + cnt < maxt_bid)

            acc = wave(jnp.ones(C, dtype=bool))
            acc = acc | wave(acc)
            acc = wave(acc)
            # each bid node is owned by exactly one shard: psum broadcasts
            # the owner's verdict to everyone
            acc_any = jax.lax.psum(acc.astype(jnp.int32), axis) > 0
            choice_g = jnp.where(acc_any, bid_g, choice_g)
            accept = accept | acc_any
            slot = jnp.where(bidding & ~acc_any,
                             jnp.minimum(slot + 1, K - 1), slot)
            return accept, choice_g, slot

        accept0 = jnp.zeros(C, dtype=bool)
        choice0 = jnp.full(C, -1, dtype=jnp.int32)
        slot0 = jnp.zeros(C, dtype=jnp.int32)
        accept, choice_g, _ = jax.lax.fori_loop(
            0, K, round_body, (accept0, choice0, slot0))

        # apply deltas on the owning shard
        mine = (accept & (choice_g >= shard_offset)
                & (choice_g < shard_offset + Nl))
        choice_l = jnp.clip(choice_g - shard_offset, 0, Nl - 1)
        placed = jax.nn.one_hot(choice_l, Nl, dtype=req.dtype) * mine[:, None]

        # alloc-vs-pipeline split (allocate.go:232-256 / ops/place.py:119):
        # within the chunk, a task allocates iff it fits the node's Idle
        # after the IDLE consumption of earlier-in-chunk allocs on the same
        # node — pipelined neighbors consume FutureIdle only. Earlier alloc
        # membership is itself the unknown; iterate the antitone fit map F:
        # after t applications the first t same-node tasks carry their
        # exact sequential value, and an ODD iterate is a SUBSET of the
        # true greedy alloc set (S0=all ⊇ true ⇒ S1=F(S0) ⊆ F(true)=true,
        # alternating), so any task still undecided at depth >9 falls on
        # the safe side — pipelined, consuming only the FutureIdle room its
        # acceptance already validated. Idle can never be oversubscribed.
        same_node = (choice_l[:, None] == choice_l[None, :]) \
            & mine[:, None] & mine[None, :] & lower
        idle_bid = nodes.idle[choice_l]

        def alloc_iter(_, alloc):
            cum = (same_node * alloc[None, :].astype(req.dtype)) @ req
            return mine & jnp.all(req + cum < idle_bid + EPS, axis=-1)

        alloc = jax.lax.fori_loop(0, 9, alloc_iter, mine)
        # one psum so every shard sees the global pipelined verdict
        alloc_any = jax.lax.psum(alloc.astype(jnp.int32), axis) > 0
        pipe = accept & ~alloc_any

        alloc_hot = placed * alloc[:, None].astype(req.dtype)
        delta_alloc = jnp.einsum("cn,cr->nr", alloc_hot, req)
        delta_all = jnp.einsum("cn,cr->nr", placed, req)
        nodes = NodeState(
            idle=nodes.idle - delta_alloc,
            future_idle=nodes.future_idle - delta_all,
            used=nodes.used + delta_alloc,
            ntasks=nodes.ntasks + jnp.sum(placed, axis=0).astype(jnp.int32))

        out = jnp.where(accept, choice_g, NO_NODE).astype(jnp.int32)
        return nodes, (out, pipe)

    return step


_SOLVER_CACHE: dict = {}


def _sharded_solver(mesh: Mesh, chunk: int, sweeps: int, passes: int,
                    has_ms: bool):
    """Compiled node-sharded solve for this mesh. jobs/weights are runtime
    args (re-tracing per cycle would pay a multi-second compile)."""
    key = (tuple(d.id for d in mesh.devices.flat), chunk, sweeps, passes,
           has_ms)
    if key in _SOLVER_CACHE:
        return _SOLVER_CACHE[key]

    node_sharded = P(NODE_AXIS)
    repl = P()
    in_specs = [NodeState(*(node_sharded,) * 4), node_sharded, node_sharded,
                repl, repl, repl,
                JobMeta(repl, repl, repl),
                ScoreWeights(repl, repl, repl, repl, repl)]
    if has_ms:
        in_specs.append(P(None, NODE_AXIS))

    @partial(shard_map_compat, mesh=mesh, in_specs=tuple(in_specs),
             out_specs=(repl, NodeState(*(node_sharded,) * 4)))
    def solve(nodes, allocatable, max_tasks, req, valid, job_ix, jobs,
              weights, *maybe_ms):
        Tp = req.shape[0]
        n_chunks = Tp // chunk
        Nl = allocatable.shape[0]
        J = jobs.min_available.shape[0]
        shard_offset = jax.lax.axis_index(NODE_AXIS) * Nl
        step = partial(_sharded_chunk_step(NODE_AXIS, has_ms),
                       allocatable=allocatable, max_tasks=max_tasks,
                       weights=weights, shard_offset=shard_offset)
        ms = maybe_ms[0] if has_ms else None

        assign0 = jnp.full(Tp, NO_NODE, dtype=jnp.int32)
        pipe0 = jnp.zeros(Tp, dtype=bool)

        def place_pass(carry, _):
            nodes, assign, pipe, job_dead = carry
            todo = (assign == NO_NODE) & valid & ~job_dead[job_ix]
            xs = (req.reshape(n_chunks, chunk, -1),
                  todo.reshape(n_chunks, chunk))
            if has_ms:
                xs = xs + (ms.reshape(n_chunks, chunk, Nl),)
            nodes, (out, out_pipe) = jax.lax.scan(step, nodes, xs)
            fresh = assign == NO_NODE
            assign = jnp.where(fresh, out.reshape(Tp), assign)
            pipe = jnp.where(fresh, out_pipe.reshape(Tp), pipe)
            return (nodes, assign, pipe, job_dead), None

        def sweep(carry, _):
            (nodes, assign, pipe, job_dead), _ = jax.lax.scan(
                place_pass, carry, jnp.arange(passes))

            placed = assign != NO_NODE
            alloc_cnt = jax.ops.segment_sum(
                (placed & ~pipe).astype(jnp.int32), job_ix, num_segments=J)
            pipe_cnt = jax.ops.segment_sum(
                (placed & pipe).astype(jnp.int32), job_ix, num_segments=J)
            # gang votes (gang.go:45-216): ready counts allocations only;
            # a merely-pipelined gang is KEPT (allocate.go:264-270 commits
            # ready jobs, keeps pipelined ones open)
            ready = alloc_cnt + jobs.base_ready >= jobs.min_available
            kept = (alloc_cnt + pipe_cnt + jobs.base_ready
                    + jobs.base_pipelined >= jobs.min_available)
            drop = placed & ~kept[job_ix]
            # free dropped demand on the owning shard (alloc'd drops free
            # Idle too; pipelined drops only reserved future capacity)
            local = (assign >= shard_offset) & (assign < shard_offset + Nl) & drop
            drop_hot = (jax.nn.one_hot(
                jnp.where(local, assign - shard_offset, 0), Nl,
                dtype=req.dtype) * local[:, None])
            alloc_hot = drop_hot * (~pipe)[:, None].astype(req.dtype)
            freed_alloc = jnp.einsum("tn,tr->nr", alloc_hot, req)
            freed_all = jnp.einsum("tn,tr->nr", drop_hot, req)
            nodes = NodeState(
                idle=nodes.idle + freed_alloc,
                future_idle=nodes.future_idle + freed_all,
                used=nodes.used - freed_alloc,
                ntasks=nodes.ntasks - jnp.sum(drop_hot, axis=0).astype(jnp.int32))
            assign = jnp.where(drop, NO_NODE, assign)
            job_dead = job_dead | (~kept & (alloc_cnt + pipe_cnt > 0))
            return (nodes, assign, pipe, job_dead), (ready, kept)

        (nodes, assign, pipe, _), (readies, kepts) = jax.lax.scan(
            sweep, (nodes, assign0, pipe0, jnp.zeros(J, dtype=bool)),
            jnp.arange(sweeps))
        # pack (assign, pipe, ready, kept) in one i32 row: one host fetch
        packed = jnp.concatenate([assign, pipe.astype(jnp.int32),
                                  readies[-1].astype(jnp.int32),
                                  kepts[-1].astype(jnp.int32)])
        return packed, nodes

    fn = jax.jit(solve)
    _SOLVER_CACHE[key] = fn
    return fn


def place_blocks_sharded(mesh: Mesh, nodes: NodeState, req: jnp.ndarray,
                         valid: jnp.ndarray, job_ix: jnp.ndarray,
                         jobs: JobMeta, weights: ScoreWeights,
                         allocatable: jnp.ndarray, max_tasks: jnp.ndarray,
                         chunk: int = 256, sweeps: int = 3, passes: int = 3,
                         masked_static: Optional[jnp.ndarray] = None,
                         ) -> Tuple[np.ndarray, np.ndarray,
                                    np.ndarray, np.ndarray, NodeState]:
    """Node-sharded block-greedy placement over ``mesh``.

    nodes/allocatable/max_tasks are sharded on the node axis; tasks
    (req/valid/job_ix) and JobMeta are replicated; ``masked_static``
    (optional f32[T,N], NEG where statically infeasible) is sharded on its
    node axis. Returns (task_node i32[T] global indices, task_pipelined
    bool[T], job_ready bool[J], job_kept bool[J] — host numpy from one
    packed fetch — and the final sharded NodeState, left on device). N
    must be divisible by the mesh size (pad with zero-capacity nodes).
    """
    D = mesh.devices.size
    N = allocatable.shape[0]
    assert N % D == 0, f"node count {N} not divisible by mesh size {D}"
    T = req.shape[0]
    pad = (-T) % chunk
    if pad:
        req = jnp.pad(req, ((0, pad), (0, 0)))
        valid = jnp.pad(valid, (0, pad))
        job_ix = jnp.pad(job_ix, (0, pad))
        if masked_static is not None:
            masked_static = jnp.pad(masked_static, ((0, pad), (0, 0)),
                                    constant_values=NEG)
    Tp = T + pad

    fn = _sharded_solver(mesh, chunk, sweeps, passes,
                         masked_static is not None)
    args = [nodes, allocatable, max_tasks, req, valid, job_ix, jobs, weights]
    if masked_static is not None:
        args.append(masked_static)
    packed, out_nodes = fn(*args)
    packed = np.asarray(packed)                       # the ONE fetch
    J = jobs.min_available.shape[0]
    return (packed[:T], packed[Tp:Tp + T].astype(bool),
            packed[2 * Tp:2 * Tp + J].astype(bool),
            packed[2 * Tp + J:].astype(bool), out_nodes)
