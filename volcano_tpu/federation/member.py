"""PartitionMember: the per-partition glue between the scheduler shell
and the federation (docs/federation.md).

One member rides each partition's Scheduler (``sched.federation``). The
shell drives it only while this replica LEADS its partition (the hooks
sit behind the HA gate), so every reserve decision is made by a live,
fenced leadership:

- ``on_cycle_start`` (before the snapshot): expire timed-out reserves,
  settle drained queue moves, review incoming reserve requests — grants
  mutate cluster state BEFORE the cycle's snapshot, so the same cycle
  schedules against the post-transfer world;
- ``on_cycle_end`` (the cycle epilogue): publish this partition's idle
  capacity to the ledger, detect starvation, and file at most one
  reserve request.

Starvation is deliberately conservative: a gang is starved only when it
has waited ``starve_after_s`` of (virtual) time without admission AND
the partition's own idle capacity cannot cover it — anything less
self-heals next cycle without cross-partition traffic.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from .partition import PartitionMap
from .reserve import ReserveLedger

log = logging.getLogger(__name__)

DEFAULT_STARVE_AFTER_S = 4.0


class PartitionMember:
    def __init__(self, pid: int, pmap: PartitionMap, ledger: ReserveLedger,
                 cache, epoch_fn: Callable[[], int],
                 time_fn: Callable[[], float] = time.monotonic,
                 starve_after_s: float = DEFAULT_STARVE_AFTER_S,
                 rebalancer=None, elastic=None):
        self.pid = pid
        self.pmap = pmap
        self.ledger = ledger
        self.cache = cache
        self.epoch_fn = epoch_fn
        self.time_fn = time_fn
        self.starve_after_s = starve_after_s
        self.requests_filed = 0
        # load-driven rebalancing (federation/rebalance.py): when a
        # RebalanceController rides this member, on_cycle_end publishes
        # load signals and may move ONE owned queue through the
        # journaled move funnel. None = the PR 9 operator-only behavior.
        self.rebalancer = rebalancer
        # elastic membership (federation/elastic.py): when an
        # ElasticController rides this member, on_cycle_end may split
        # this partition or drive its merge. None = fixed membership.
        self.elastic = elastic
        ledger.attach_cache(pid, cache)

    # -- cycle hooks (leader-gated by the scheduler shell) -------------------

    def on_cycle_start(self) -> None:
        # store-backed maps (federation/store_backed.py) first heal a
        # torn PartitionState stream so this cycle reviews against the
        # freshest ownership/request state reachable
        sync = getattr(self.pmap, "sync", None)
        if sync is not None:
            sync()
        epoch = self.epoch_fn()
        self.ledger.expire(self.time_fn())
        self.ledger.settle_moves(self.pid, epoch)
        self.ledger.review(self.pid, epoch)

    def publish_follower(self) -> None:
        """Publish this replica's NON-leading state for its partition —
        called by the scheduler shell's HA gate on every follower cycle
        (the on_cycle_* hooks are leader-gated, so without this a
        deposed replica would export a stale leading=1 gauge forever
        and monitoring would show two leaders after a failover)."""
        from .. import metrics
        metrics.set_partition_leader(self.pid, False, self.epoch_fn(),
                                     detail=self.detail())

    def on_cycle_end(self) -> None:
        from .. import metrics
        now = self.time_fn()
        idle_cpu, idle_mem = self._owned_idle()
        self.ledger.publish_idle(self.pid, idle_cpu, idle_mem)
        if self.rebalancer is not None:
            # publish this partition's load signals and (hysteresis +
            # flap guard permitting) move at most one owned queue
            # through the journaled move_queue/settle_moves funnel —
            # isolated: a rebalancer fault must not cost the cycle
            try:
                self.rebalancer.step(now)
            except Exception:
                log.exception("rebalancer step failed; next cycle "
                              "re-evaluates")
        if self.elastic is not None:
            # the membership decision (split/merge) — isolated the same
            # way: an elastic fault must not cost the scheduling cycle
            try:
                self.elastic.step(now)
            except Exception:
                log.exception("elastic step failed; next cycle "
                              "re-evaluates")
        metrics.set_partition_leader(self.pid, True, self.epoch_fn(),
                                    detail=self.detail())
        starved = self._starved_need(now, idle_cpu, idle_mem)
        if starved is None:
            return
        need_cpu, need_mem = starved
        if self.ledger.outstanding(self.pid) is not None:
            return
        donor = self.ledger.pick_donor(self.pid)
        if donor is None:
            return
        rid = self.ledger.request(self.pid, donor, need_cpu, need_mem,
                                  self.epoch_fn())
        if rid is not None:
            self.requests_filed += 1
            log.warning("partition %d starved: reserved (%.0f mcpu, "
                        "%.0f B) from partition %d (rid=%d)",
                        self.pid, need_cpu, need_mem, donor, rid)

    # -- starvation detection ------------------------------------------------

    def _owned_idle(self) -> tuple:
        cpu = mem = 0.0
        for name in self.pmap.unpinned_nodes_of(self.pid):
            node = self.cache.nodes.get(name)
            if node is None or not node.ready:
                continue
            cpu += node.idle.cpu
            mem += node.idle.memory
        return cpu, mem

    def _starved_need(self, now: float, idle_cpu: float,
                      idle_mem: float) -> Optional[tuple]:
        """The oldest unadmitted gang that has waited past the
        starvation horizon and does not fit the partition's own idle
        capacity; returns its outstanding (cpu, mem) demand. Pending
        gangs that FIT are not starved — they place next cycle."""
        from ..api import TaskStatus
        oldest = None
        oldest_age = self.starve_after_s
        for job in self.cache.jobs.values():
            if job.min_available <= 0 or job.ready():
                continue
            born = job.schedule_start_timestamp
            if born is None:
                born = job.creation_timestamp or 0.0
            age = now - float(born)
            if age < oldest_age:
                continue
            cpu = mem = 0.0
            for task in job.tasks.values():
                if task.status == TaskStatus.PENDING:
                    cpu += task.resreq.cpu
                    mem += task.resreq.memory
            if cpu <= 0 and mem <= 0:
                continue
            if cpu <= idle_cpu and mem <= idle_mem:
                continue                   # fits locally: not starvation
            if oldest is None or (age, job.uid) > oldest[:2]:
                oldest = (age, job.uid, cpu, mem)
        if oldest is None:
            return None
        return oldest[2], oldest[3]

    # -- introspection (/healthz?detail, vcctl) ------------------------------

    def detail(self) -> dict:
        counts = self.pmap.counts().get(self.pid, {})
        out = {
            "partition": self.pid,
            "epoch": self.epoch_fn(),
            "queues": counts.get("queues", 0),
            "nodes": counts.get("nodes", 0),
            "requests_filed": self.requests_filed,
            "map_version": self.pmap.version,
        }
        if self.rebalancer is not None:
            out["rebalance_moves"] = len(self.rebalancer.moves)
        if self.elastic is not None:
            out["splits"] = self.elastic.splits
            out["merges"] = self.elastic.merges
            out["retiring"] = self.elastic.retiring
        return out
