"""Store-backed federation transport (docs/federation.md): the
PartitionMap and ReserveLedger as a ``PartitionState`` CR flowing
through the store's CAS/watch path — what real multi-process
deployments run, closing ROADMAP item 5's remaining gap.

Topology: every partition process holds its OWN
:class:`StoreBackedPartitionMap` / :class:`StoreBackedReserveLedger`
mirror over a shared :class:`StorePartitionBackend`. Writes go through
``backend.mutate`` — read the CR, apply the transition to a deep copy
of its one-dict spec, CAS it back (``update(expect_rv=...)``), retrying
on :class:`ConflictError` with a fresh read. Remote writes arrive on a
resumable PartitionState watch and replace the mirror wholesale.

The two-phase reserve/transfer protocol stays correct under store
chaos BY this shape:

- a transition either CASes (one atomic spec replacement — other
  partitions see all of it or none of it) or raises out of ``mutate``
  into the federation hook's isolation: nothing was half-written, and
  the request's deadline still stands, so the pin releases by expiry —
  grants and ownership flips land atomically or time out and release;
- ownership flips are PERSIST-FIRST: ``_transfer_node_raw`` writes the
  CR before touching the local mirror, so a flip every other partition
  can see is also the flip the owner acts on (never the reverse —
  locally-flipped-but-unpublished would strand the node);
- a torn PartitionState watch merely staves a mirror: reviews pause,
  ``sync()`` (driven from the partition's cycle hooks) resumes/relists
  the stream, and deadlines bound every in-flight exchange meanwhile.

vlint VT016 exempts this module by name: the CAS loop here IS a store
write funnel, with retry semantics (fresh-read-and-reapply) that the
generic retrying transport cannot provide.
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Callable, Dict, List, Optional

from ..apis.objects import ObjectMeta, PartitionStateCR
from ..store import ConflictError
from .partition import PartitionMap
from .reserve import _OPEN, ReserveLedger, ReserveRequest

log = logging.getLogger(__name__)

PARTITION_STATE_NS = "volcano-system"
PARTITION_STATE_NAME = "partition-state"
DEFAULT_CAS_ATTEMPTS = 8


class StateExhaustedError(RuntimeError):
    """A PartitionState CAS loop ran out of attempts (hot contention or
    a sick store past the retry funnel). The caller's transition did NOT
    happen; deadlines own the cleanup."""


class NoChange(Exception):
    """Raised by a mutate() transition fn to abort WITHOUT writing (the
    state already reflects the transition — e.g. an idempotent
    re-registration); carries the return value."""

    def __init__(self, value=None):
        super().__init__("no change")
        self.value = value


def _initial_state(n: int) -> dict:
    return {"n": int(n), "queue_owner": {}, "node_owner": {},
            "pinned": {}, "draining": {}, "rr_queue": 0, "rr_node": 0,
            "idle": {}, "requests": {}, "next_rid": 1,
            "active": {p: "active" for p in range(int(n))},
            "next_pid": int(n), "version": 0}


def _state_active(state: dict) -> dict:
    """The membership map off a CR spec, tolerating pre-elastic CRs
    that predate the ``active`` field (static {0..n-1} membership)."""
    active = state.get("active")
    if active is None:
        active = {p: "active" for p in range(int(state["n"]))}
    return active


class StorePartitionBackend:
    """One partition process's connection to the PartitionState CR:
    the CAS write funnel plus a resumable watch keeping the attached
    mirrors (map + ledger) converged."""

    def __init__(self, store, n_partitions: int,
                 namespace: str = PARTITION_STATE_NS,
                 name: str = PARTITION_STATE_NAME,
                 cas_attempts: int = DEFAULT_CAS_ATTEMPTS):
        self.store = store
        self.n = int(n_partitions)
        self.namespace = namespace
        self.name = name
        self.cas_attempts = max(int(cas_attempts), 1)
        self._listeners: List[Callable[[dict], None]] = []
        self._watch = None
        self.cas_conflicts = 0
        self.ensure()
        from ..cache.watches import ResumableWatch
        self._watch = ResumableWatch(store, "PartitionState",
                                     self._on_event)

    # -- wiring --------------------------------------------------------------

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        self._listeners.append(fn)
        obj = self.store.get("PartitionState", self.namespace, self.name)
        if obj is not None:
            fn(obj.spec)

    def _on_event(self, event: str, obj, old) -> None:
        if obj is None or event == "deleted":
            return
        for fn in self._listeners:
            fn(obj.spec)

    def sync(self) -> None:
        """Resume the PartitionState stream if it tore (the partition's
        cycle-start hook drives this; a stale mirror self-heals here)."""
        if self._watch is not None and self._watch.torn:
            self._watch.resume()

    # -- the CAS funnel ------------------------------------------------------

    def ensure(self) -> None:
        """Create the CR if absent (CAS create-only, race-safe)."""
        if self.store.get("PartitionState", self.namespace,
                          self.name) is not None:
            return
        obj = PartitionStateCR(
            metadata=ObjectMeta(name=self.name, namespace=self.namespace),
            spec=_initial_state(self.n))
        try:
            self.store.update(obj, expect_rv=0)
        except ConflictError:
            pass                          # another partition won the race

    def mutate(self, fn: Callable[[dict], object]):
        """Apply ``fn`` to a deep copy of the CR spec and CAS it back;
        on conflict, re-read and re-apply. ``fn`` may raise to abort
        (nothing written). Returns ``fn``'s return value. Raises
        :class:`StateExhaustedError` past the attempt budget and lets
        transient store errors (already retried by the transport
        funnel) propagate — either way the transition did not happen."""
        for _ in range(self.cas_attempts):
            obj = self.store.get("PartitionState", self.namespace,
                                 self.name)
            if obj is None:
                self.ensure()
                continue
            state = copy.deepcopy(obj.spec)
            try:
                out = fn(state)
            except NoChange as nc:
                return nc.value
            state["version"] = int(state.get("version", 0)) + 1
            new = PartitionStateCR(
                metadata=ObjectMeta(name=self.name,
                                    namespace=self.namespace),
                spec=state)
            try:
                self.store.update(new,
                                  expect_rv=obj.metadata.resource_version)
                return out
            except ConflictError:
                self.cas_conflicts += 1
                continue
        raise StateExhaustedError(
            f"PartitionState CAS exhausted after {self.cas_attempts} "
            f"attempts ({self.cas_conflicts} conflicts total)")


class StoreBackedPartitionMap(PartitionMap):
    """PartitionMap mirror whose ownership state lives on the
    PartitionState CR. Registration and the raw transfer mutators (the
    VT009 funnel targets — still only callable from the reserve
    funnel) go through the backend's CAS loop; remote writes land via
    the watch. Ownership FLIPS persist before they apply locally (see
    the module docstring's atomicity argument)."""

    def __init__(self, backend: StorePartitionBackend):
        super().__init__(backend.n)
        self.backend = backend
        backend.add_listener(self._apply_state)

    def sync(self) -> None:
        self.backend.sync()

    # -- mirror application --------------------------------------------------

    def _apply_state(self, state: dict) -> None:
        with self._lock:
            self.queue_owner = dict(state.get("queue_owner", {}))
            self.node_owner = dict(state.get("node_owner", {}))
            self.pinned = dict(state.get("pinned", {}))
            self.draining = dict(state.get("draining", {}))
            self._rr_queue = int(state.get("rr_queue", 0))
            self._rr_node = int(state.get("rr_node", 0))
            self.active = {int(p): s
                           for p, s in _state_active(state).items()}
            self.next_pid = int(state.get("next_pid", state.get("n", 0)))
            self.version = int(state.get("version", 0))

    # -- registration (watch stream; CAS-allocated round-robin) --------------

    def register_queue(self, name: str) -> int:
        with self._lock:
            if name in self.queue_owner:
                return self.queue_owner[name]

        def assign(state: dict) -> int:
            owner = state["queue_owner"].get(name)
            if owner is not None:
                raise NoChange(owner)     # idempotent re-registration
            pids = sorted(int(p) for p, s in _state_active(state).items()
                          if s == "active")
            owner = pids[state["rr_queue"] % len(pids)]
            state["queue_owner"][name] = owner
            state["rr_queue"] += 1
            return owner

        return self.backend.mutate(assign)

    def register_node(self, name: str) -> int:
        with self._lock:
            if name in self.node_owner:
                return self.node_owner[name]

        def assign(state: dict) -> int:
            owner = state["node_owner"].get(name)
            if owner is not None:
                raise NoChange(owner)
            pids = sorted(int(p) for p, s in _state_active(state).items()
                          if s == "active")
            owner = pids[state["rr_node"] % len(pids)]
            state["node_owner"][name] = owner
            state["rr_node"] += 1
            return owner

        return self.backend.mutate(assign)

    def forget_node(self, name: str) -> None:
        def drop(state: dict) -> None:
            if name not in state["node_owner"] \
                    and name not in state["pinned"]:
                raise NoChange()
            state["node_owner"].pop(name, None)
            state["pinned"].pop(name, None)

        self.backend.mutate(drop)

    # -- ownership transfer (reserve funnel only; persist-first) -------------

    def _transfer_node_raw(self, node: str, to: int) -> None:
        def flip(state: dict) -> None:
            state["node_owner"][node] = to
            state["pinned"].pop(node, None)

        self.backend.mutate(flip)

    def _transfer_queue_raw(self, queue: str, to: int) -> None:
        def flip(state: dict) -> None:
            state["queue_owner"][queue] = to
            state["draining"].pop(queue, None)

        self.backend.mutate(flip)

    def _pin_node_raw(self, node: str, rid: Optional[int]) -> None:
        def pin(state: dict) -> None:
            if rid is None:
                state["pinned"].pop(node, None)
            else:
                state["pinned"][node] = rid

        self.backend.mutate(pin)

    def _begin_drain_raw(self, queue: str, to: int) -> None:
        def drain(state: dict) -> None:
            state["draining"][queue] = to

        self.backend.mutate(drain)

    # -- elastic membership (spawn/retire funnel only; persist-first) --------

    def _spawn_partition_raw(self) -> int:
        def spawn(state: dict) -> int:
            active = _state_active(state)
            pid = int(state.get("next_pid", state["n"]))
            active[pid] = "active"
            state["active"] = active
            state["next_pid"] = pid + 1
            return pid

        pid = self.backend.mutate(spawn)
        # the watch echo replaces the mirror wholesale; apply eagerly
        # too so the caller's active_pids() sees the pid it just minted
        with self._lock:
            self.active[pid] = "active"
            self.next_pid = max(self.next_pid, pid + 1)
            self.version += 1
        return pid

    def _begin_retire_raw(self, pid: int) -> None:
        def mark(state: dict) -> None:
            active = _state_active(state)
            if str(pid) in active:
                active[str(pid)] = "retiring"
            elif pid in active:
                active[pid] = "retiring"
            else:
                raise NoChange()
            state["active"] = active

        self.backend.mutate(mark)
        with self._lock:
            if pid in self.active:
                self.active[pid] = "retiring"
                self.version += 1

    def _retire_partition_raw(self, pid: int) -> None:
        def drop(state: dict) -> None:
            active = _state_active(state)
            if active.pop(str(pid), None) is None \
                    and active.pop(pid, None) is None:
                raise NoChange()
            state["active"] = active

        self.backend.mutate(drop)
        with self._lock:
            self.active.pop(pid, None)
            self.version += 1


class StoreBackedReserveLedger(ReserveLedger):
    """ReserveLedger mirror whose OPEN request set lives on the
    PartitionState CR: the requester files through CAS, the owner's
    mirror sees it via the watch, every transition persists, and a
    settled request leaves the CR (the journal's control records stay
    the durable audit trail). Protocol logic is entirely inherited —
    only rid allocation, idle publication and the persistence hooks
    differ."""

    _REQ_FIELDS = ("rid", "frm", "to", "cpu", "mem", "created",
                   "deadline", "state", "epoch_from", "epoch_to_observed",
                   "node", "epoch_granted")

    def __init__(self, pmap: StoreBackedPartitionMap,
                 backend: StorePartitionBackend, **kwargs):
        super().__init__(pmap, **kwargs)
        self.backend = backend
        backend.add_listener(self._apply_state)

    # -- hooks ---------------------------------------------------------------

    def _alloc_rid(self) -> int:
        def bump(state: dict) -> int:
            rid = int(state.get("next_rid", 1))
            state["next_rid"] = rid + 1
            return rid

        return self.backend.mutate(bump)

    def _persist_request(self, req: ReserveRequest) -> None:
        d = {k: getattr(req, k) for k in self._REQ_FIELDS}

        def put(state: dict) -> None:
            state["requests"][req.rid] = d

        self.backend.mutate(put)

    def _drop_request(self, req: ReserveRequest) -> None:
        def drop(state: dict) -> None:
            state["requests"].pop(req.rid, None)

        try:
            self.backend.mutate(drop)
        except Exception:
            # a settle whose CR removal failed: every partition's expire
            # scan still bounds the leftover open record by its deadline
            log.exception("dropping settled reserve %d from the CR "
                          "failed; deadline expiry owns the cleanup",
                          req.rid)

    def publish_idle(self, pid: int, cpu: float, mem: float) -> None:
        super().publish_idle(pid, cpu, mem)

        def put(state: dict) -> None:
            state["idle"][pid] = (float(cpu), float(mem))

        self.backend.mutate(put)

    def publish_load(self, pid: int, load: dict) -> None:
        """The rebalancer's load signals persist to the PartitionState
        CR next to idle — other partitions' rebalancers read them off
        their own CR mirrors (docs/federation.md)."""
        super().publish_load(pid, load)

        def put(state: dict) -> None:
            state.setdefault("load", {})[pid] = dict(load)

        self.backend.mutate(put)

    def _persist_membership_purge(self, pid: int) -> None:
        def purge(state: dict) -> None:
            hit = False
            for key in ("idle", "load"):
                table = state.get(key, {})
                if table.pop(pid, None) is not None \
                        or table.pop(str(pid), None) is not None:
                    hit = True
            if not hit:
                raise NoChange()

        try:
            self.backend.mutate(purge)
        except Exception:
            # a purge whose CR write failed leaves stale idle/load
            # entries for a pid no longer in the membership — harmless:
            # every reader iterates active pids, never these tables
            log.exception("purging retired partition %d from the CR "
                          "failed", pid)

    # -- mirror application --------------------------------------------------

    def _apply_state(self, state: dict) -> None:
        reqs = state.get("requests", {})
        with self._lock:
            for pid, pair in state.get("idle", {}).items():
                self._idle[int(pid)] = (float(pair[0]), float(pair[1]))
            for pid, load in state.get("load", {}).items():
                # change-detected receipt stamping (_apply_load_locked):
                # a watch echo re-delivering an unchanged entry must not
                # refresh a dead publisher's freshness
                self._apply_load_locked(int(pid), dict(load))
            for rid, d in reqs.items():
                rid = int(rid)
                req = self.requests.get(rid)
                if req is None:
                    req = ReserveRequest(
                        rid, d["frm"], d["to"], d["cpu"], d["mem"],
                        d["created"], d["deadline"], d["epoch_from"],
                        d["epoch_to_observed"])
                    self.requests[rid] = req
                req.state = d["state"]
                req.node = d.get("node", "")
                req.deadline = d["deadline"]
                req.epoch_granted = d.get("epoch_granted", 0)
            # open requests gone from the CR were settled by another
            # partition: drop them from the mirror without re-counting
            # (the settling partition counted; the journal has the trail)
            for rid in [r for r in self.requests
                        if r not in reqs
                        and self.requests[r].state in _OPEN]:
                del self.requests[rid]
