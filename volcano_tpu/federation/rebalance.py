"""Load-driven partition rebalancing (docs/federation.md; closes
ROADMAP item 5's remainder).

PR 9's federation sheds hot-partition load only through operator
``move_queue`` calls; this controller drives the SAME journaled funnel
from observed load instead. Every partition's leader runs one
:class:`RebalanceController` at its cycle end:

1. **publish** — compute this partition's load signals (pending task
   depth per owned queue, the cycle-budget exhaustion rate, total
   depth) and publish them through the reserve ledger (in-process: the
   shared board; store-backed: the PartitionState CR — other
   partitions read their own CR mirrors, never this cache);
2. **decide** — a deterministic greedy bin-balancer over LAST cycle's
   published signals: if this partition's pending depth exceeds the
   coolest partition's by both an absolute gap and a ratio (the
   hysteresis that keeps borderline imbalance from churning), pick the
   owned queue whose depth best halves the gap (largest depth <=
   gap/2, falling back to the largest depth < gap — a dominating hot
   queue still moves when moving it reduces imbalance);
3. **guard** — a device_health-style flap guard: each time a queue
   moves, its next move is refused for a DOUBLING abstention window
   (capped), so oscillating load cannot ping-pong a queue between
   partitions;
4. **execute** — ``ledger.move_queue(queue, target, epoch)``: the
   existing journaled, leader-gated, epoch-fenced two-phase move
   funnel. The queue drains (NEITHER side schedules it) and
   ``settle_moves`` flips ownership byte-deterministically — the
   rebalancer adds a decision layer, never a new mutation path (vlint
   VT009 still holds: ownership writes stay inside the reserve
   funnel).

Only the OWNING partition's leader may initiate a move of its queue
(``move_queue`` refuses deposed epochs), so concurrent rebalancers
cannot fight over one queue; distinct hot partitions shed independently.
All inputs are published snapshots + the injectable clock, so
``sim --federated`` replays byte-identically.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

DEFAULT_MIN_DEPTH = 8          # below this, a partition is never "hot"
DEFAULT_MIN_GAP = 8            # absolute pending-depth hysteresis
DEFAULT_RATIO = 2.0            # hot/cool ratio hysteresis
DEFAULT_COOLDOWN_S = 8.0       # first per-queue abstention window
DEFAULT_MAX_COOLDOWN_S = 128.0


class RebalanceController:
    """One partition's slice of the load-driven rebalancer."""

    def __init__(self, pid: int, pmap, ledger, cache,
                 epoch_fn: Callable[[], int],
                 time_fn: Callable[[], float] = time.monotonic,
                 exhausted_fn: Optional[Callable[[], int]] = None,
                 min_depth: int = DEFAULT_MIN_DEPTH,
                 min_gap: int = DEFAULT_MIN_GAP,
                 ratio: float = DEFAULT_RATIO,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 max_cooldown_s: float = DEFAULT_MAX_COOLDOWN_S,
                 stale_after_s: Optional[float] = None):
        self.pid = pid
        self.pmap = pmap
        self.ledger = ledger
        self.cache = cache
        self.epoch_fn = epoch_fn
        self.time_fn = time_fn
        # reads the shell's cycle-budget exhaustion counter (monotonic);
        # the published rate is its per-step delta
        self.exhausted_fn = exhausted_fn or (lambda: 0)
        self.min_depth = int(min_depth)
        self.min_gap = int(min_gap)
        self.ratio = float(ratio)
        self.cooldown_s = float(cooldown_s)
        self.max_cooldown_s = float(max_cooldown_s)
        # published signals older than this are not trusted: a silent
        # (leaderless, torn-mirror) partition must never look like the
        # coolest move target — nothing drains a queue handed to a
        # partition that stopped publishing
        self.stale_after_s = float(stale_after_s) \
            if stale_after_s is not None else 2.0 * self.cooldown_s
        self._last_exhausted = 0
        self._steps = 0
        # flap guard state: queue -> (times moved, abstain-until)
        self._queue_moves: Dict[str, int] = {}
        self._queue_block: Dict[str, float] = {}
        # queues owned at the last step: a queue that APPEARS here mid-
        # run just arrived from another partition's rebalancer — give it
        # a settle window before this controller may move it on, or two
        # still-warm partitions hop one hot queue around the ring
        # instead of letting the new home drain it
        self._owned_prev: set = set()
        self.moves: List[dict] = []        # executed move history
        self.abstentions = 0
        self.refused = 0

    # -- load signals --------------------------------------------------------

    def queue_depths(self) -> Dict[str, int]:
        """Pending task count per queue this partition owns (live cache
        read — published for OTHERS to consume next cycle). The walk is
        bounded by the partition's own job population (the per-queue
        admission depth bound, vlint VT018's budget witness is the
        admission limit upstream)."""
        from ..api import TaskStatus
        owned = set(self.pmap.queues_of(self.pid))
        depths = {q: 0 for q in sorted(owned)}
        for job in self.cache.jobs.values():
            if job.queue not in owned:
                continue
            n = len(job.task_status_index.get(TaskStatus.PENDING, {}))
            if n:
                depths[job.queue] += n
        return depths

    def publish(self, now: Optional[float] = None) -> dict:
        now = self.time_fn() if now is None else now
        depths = self.queue_depths()
        exhausted = int(self.exhausted_fn())
        delta, self._last_exhausted = \
            exhausted - self._last_exhausted, exhausted
        self._steps += 1
        load = {
            "pending": sum(depths.values()),
            "queues": depths,
            "exhausted_delta": max(delta, 0),
            "t": round(now, 6),
        }
        self.ledger.publish_load(self.pid, load)
        return load

    # -- the decision --------------------------------------------------------

    def _flap_blocked(self, queue: str, now: float) -> bool:
        until = self._queue_block.get(queue)
        return until is not None and now < until

    def _note_move(self, queue: str, now: float) -> None:
        n = self._queue_moves.get(queue, 0) + 1
        self._queue_moves[queue] = n
        window = min(self.cooldown_s * (2 ** (n - 1)),
                     self.max_cooldown_s)
        self._queue_block[queue] = now + window

    def _pick_queue(self, depths: Dict[str, int], gap: int,
                    now: float) -> Optional[str]:
        """The greedy bin-balance choice: largest-depth owned queue that
        halves the gap, else the largest that still shrinks it. Never
        the last queue; never a flap-blocked or draining queue; never an
        empty one (moving idle queues is churn, not balance)."""
        candidates = [(d, q) for q, d in depths.items()
                      if 0 < d < gap
                      and not self._flap_blocked(q, now)
                      and q not in self.pmap.draining]
        # the last-queue guard counts queues that would REMAIN after
        # already-draining ones settle: a two-queue partition whose
        # first move is still draining must not move its second queue
        # too (both settle -> zero owned queues, a stranded node shard)
        settled = [q for q in depths if q not in self.pmap.draining]
        if len(settled) < 2 or not candidates:
            return None
        candidates.sort(key=lambda p: (-p[0], p[1]))
        for d, q in candidates:
            if d <= gap / 2:
                return q
        return candidates[0][1]            # dominating queue: still helps

    def step(self, now: Optional[float] = None) -> Optional[dict]:
        """One leader-gated cycle-end pass: publish, then move at most
        ONE queue when the hysteresis says this partition is genuinely
        hot. Returns the executed move record, or None."""
        from .. import metrics
        now = self.time_fn() if now is None else now
        load = self.publish(now)
        owned = set(load["queues"])
        if self._owned_prev:
            for q in owned - self._owned_prev:
                self._queue_block[q] = max(
                    self._queue_block.get(q, 0.0),
                    now + self.cooldown_s)
        self._owned_prev = owned
        move = self._decide(load, now)
        metrics.set_rebalance_detail(self.pid, self.detail())
        return move

    def _decide(self, load: dict, now: float) -> Optional[dict]:
        from .. import metrics
        own = int(load["pending"])
        if own < max(self.min_depth, 1):
            return None
        loads = self.ledger.loads()
        coolest = None
        coolest_pending = None
        # assignable pids only: a retired partition's stale load entry
        # (or a retiring one mid-drain) must never be a move target —
        # the elastic retire funnel also purges its ledger signals
        for pid in self.pmap.assignable_pids():
            if pid == self.pid:
                continue
            other = loads.get(pid)
            # freshness on the LOCAL receipt clock (ledger.load_seen):
            # the published dict's own timestamp is the publisher's
            # monotonic reading, not comparable across processes
            seen = self.ledger.load_seen(pid)
            if other is None or seen is None \
                    or now - seen > self.stale_after_s:
                # never published, or went silent: unknown is not idle
                continue
            pending = int(other.get("pending", 0))
            if coolest_pending is None or (pending, pid) \
                    < (coolest_pending, coolest):
                coolest, coolest_pending = pid, pending
        if coolest is None:
            return None
        gap = own - coolest_pending
        # hysteresis: both an absolute gap and a ratio must hold, so a
        # borderline imbalance (or one the last move already fixed)
        # never churns a queue back and forth
        if gap < self.min_gap or own < self.ratio * max(coolest_pending,
                                                        1):
            return None
        queue = self._pick_queue(dict(load["queues"]), gap, now)
        if queue is None:
            self.abstentions += 1
            metrics.register_rebalance_move("abstained")
            return None
        if not self.ledger.move_queue(queue, coolest, self.epoch_fn()):
            # deposed epoch, already draining, or ownership raced — the
            # funnel said no; nothing happened
            self.refused += 1
            metrics.register_rebalance_move("refused")
            return None
        self._note_move(queue, now)
        rec = {"t": round(now, 6), "queue": queue, "frm": self.pid,
               "to": coolest, "own_pending": own,
               "target_pending": coolest_pending}
        self.moves.append(rec)
        metrics.register_rebalance_move("moved")
        log.warning("rebalance: partition %d (pending %d) moving queue "
                    "%r to partition %d (pending %d)", self.pid, own,
                    queue, coolest, coolest_pending)
        return rec

    # -- introspection (vcctl federation rebalance-status) -------------------

    def detail(self) -> dict:
        return {
            "partition": self.pid,
            "moves": len(self.moves),
            "abstentions": self.abstentions,
            "refused": self.refused,
            "last_move": dict(self.moves[-1]) if self.moves else None,
            "blocked_queues": {
                q: round(until, 3)
                for q, until in sorted(self._queue_block.items())
                if until > self.time_fn()},
            "thresholds": {"min_depth": self.min_depth,
                           "min_gap": self.min_gap,
                           "ratio": self.ratio},
        }
