"""The cross-partition reserve/transfer protocol (docs/federation.md).

A starved partition cannot simply take capacity another partition owns —
that is a write to foreign cluster state, the federated analogue of the
split-brain double-bind. Instead every cross-partition reclaim flows
through this two-phase funnel, coordinated through the shared intent
journal:

1. **reserve** — the requester journals a ``reserve`` record naming the
   owning partition, the capacity it needs, its own fencing epoch AND
   the owner epoch it observed (both partitions' leaderships are named
   in the intent), and a virtual-time deadline;
2. **review** — the owner, at its next cycle boundary (leader-gated by
   the scheduler shell), grants or rejects. A grant picks a donor node,
   **pins** it (the owner's scope drops it, so the owner cannot refill
   capacity it is handing over), drains it by evicting the owner's own
   tasks through the owner's journaled+fenced evict funnel, and — once
   empty — journals the ``reserve_grant`` and flips the node's
   ownership in the PartitionMap;
3. **timeout-based release** — a request (or a half-granted pin) whose
   deadline passes is expired by WHICHEVER partition's cycle notices
   first, unpinning the node. A killed partition can therefore never
   strand capacity: its outstanding requests expire, its half-drained
   pins release, and the journal carries the full audit trail.

Queue moves (rebalancing a queue between partitions) ride the same
funnel: ``move_queue`` journals the move and marks the queue draining —
NEITHER partition schedules it — and ``settle_moves`` flips ownership
only once no open journal intent references the queue's jobs (no
orphaned intents, no double-binds across the flip).

All PartitionMap ownership transfers happen HERE, next to their
``_journal_reserve`` records — vlint rule VT009 enforces that no other
code path calls the raw transfer mutators (docs/static-analysis.md).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from ..obs.lifecycle import TIMELINE
from ..obs.trace import TRACE as OBS_TRACE
from .partition import PartitionMap

log = logging.getLogger(__name__)

DEFAULT_TIMEOUT_S = 8.0

REQUESTED = "requested"
GRANTING = "granting"      # node pinned, owner draining it
GRANTED = "granted"        # ownership transferred
REJECTED = "rejected"
EXPIRED = "expired"

_OPEN = (REQUESTED, GRANTING)


class ReserveRequest:
    """One cross-partition reserve, from journal record to settlement."""

    __slots__ = ("rid", "frm", "to", "cpu", "mem", "created", "deadline",
                 "state", "epoch_from", "epoch_to_observed", "node",
                 "epoch_granted")

    def __init__(self, rid: int, frm: int, to: int, cpu: float, mem: float,
                 created: float, deadline: float, epoch_from: int,
                 epoch_to_observed: int):
        self.rid = rid
        self.frm = frm                     # requesting partition
        self.to = to                       # owning partition
        self.cpu = float(cpu)
        self.mem = float(mem)
        self.created = created
        self.deadline = deadline
        self.state = REQUESTED
        self.epoch_from = epoch_from
        self.epoch_to_observed = epoch_to_observed
        self.node = ""                     # donor node once chosen
        self.epoch_granted = 0

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class ReserveLedger:
    """The shared reserve/transfer coordinator: in-process it is this
    object over the shared journal; a store-wired deployment would keep
    the same records in the store (the journal stream already crosses
    the process boundary via FileTailer). Thread-safe; all timestamps
    come from the injectable ``time_fn`` so ``sim --federated`` replays
    byte-deterministically."""

    def __init__(self, pmap: PartitionMap, journal=None, registry=None,
                 time_fn=time.monotonic,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 donor_guard: bool = False):
        self.pmap = pmap
        self.journal = journal
        self.registry = registry           # executors.FencingRegistry
        self.time_fn = time_fn
        self.timeout_s = timeout_s
        # Opt-in (elastic membership wires it on): a donor with its own
        # unadmitted gangs only donates EMPTY nodes. Off by default so
        # the static-federation decision plane is unchanged.
        self.donor_guard = donor_guard
        # reentrant: the store-backed subclass persists transitions
        # through a CAS funnel whose watch echo applies remote state
        # back onto this ledger's mirror — possibly on the same thread
        # that holds the lock mid-_settle
        self._lock = threading.RLock()
        self._rid = itertools.count(1)
        # OPEN requests only; settled ones move to the bounded history
        # below (the journal is the durable record), so a persistently
        # starved deployment filing one rejected request per cycle
        # cannot grow this dict — or the per-cycle scans — forever
        self.requests: Dict[int, ReserveRequest] = {}
        self.settled: "OrderedDict[int, ReserveRequest]" = OrderedDict()
        self.settled_keep = 64
        self.counts: Dict[str, int] = {}
        self.node_transfers = 0
        self.queue_moves = 0
        self._caches: Dict[int, object] = {}
        # pid -> (idle_cpu, idle_mem) published at each cycle end; the
        # requester's donor choice reads LAST cycle's published values,
        # never another partition's live cache
        self._idle: Dict[int, tuple] = {}
        # pid -> load-signal dict (pending depth, budget-exhaustion
        # rate, per-queue depths) published at each cycle end — the
        # load-driven rebalancer's only cross-partition input
        # (docs/federation.md): decisions read LAST cycle's published
        # signals, never another partition's live cache
        self._load: Dict[int, dict] = {}
        # pid -> LOCAL receipt time of the last load-signal CHANGE.
        # Freshness must be judged on the READER's clock: the published
        # dict carries the publisher's own timestamp, and monotonic
        # epochs are not comparable across processes/hosts (the
        # store-backed deployment). An entry whose value stops changing
        # stops refreshing its receipt — a dead publisher goes stale no
        # matter what its last self-stamp claims.
        self._load_seen: Dict[int, float] = {}

    # -- wiring --------------------------------------------------------------

    def attach_cache(self, pid: int, cache) -> None:
        """Bind a partition's SchedulerCache (survives that partition's
        process restarts in the sim — cluster truth does not die with a
        scheduler)."""
        self._caches[pid] = cache

    def publish_idle(self, pid: int, cpu: float, mem: float) -> None:
        with self._lock:
            self._idle[pid] = (float(cpu), float(mem))

    def publish_load(self, pid: int, load: dict) -> None:
        """Publish a partition's load signals for the rebalancer
        (federation/rebalance.py); in-process the ledger IS the shared
        board, the store-backed subclass persists to the PartitionState
        CR."""
        with self._lock:
            self._apply_load_locked(pid, dict(load))

    def _apply_load_locked(self, pid: int, load: dict) -> None:
        """Caller holds self._lock: store a load signal and stamp its
        LOCAL receipt time iff the value changed (re-applying an
        unchanged entry — every CR watch echo re-delivers the whole
        state — must not keep a dead publisher looking fresh)."""
        if self._load.get(pid) != load:
            self._load_seen[pid] = self.time_fn()
        self._load[pid] = load

    def loads(self) -> Dict[int, dict]:
        """Every partition's last-published load signals (copies)."""
        with self._lock:
            return {pid: dict(d) for pid, d in self._load.items()}

    def load_seen(self, pid: int) -> Optional[float]:
        """LOCAL receipt time of ``pid``'s last load-signal change (the
        rebalancer's freshness witness), or None if never seen."""
        with self._lock:
            return self._load_seen.get(pid)

    def _count(self, result: str, n: int = 1) -> None:
        """Caller holds self._lock."""
        self.counts[result] = self.counts.get(result, 0) + n
        from .. import metrics
        metrics.register_cross_partition_reserve(result, n)

    def _settle(self, req: ReserveRequest, state: str) -> None:
        """Caller holds self._lock: move a request from the open set to
        the bounded settled history and count the outcome."""
        req.state = state
        self.requests.pop(req.rid, None)
        self.settled[req.rid] = req
        while len(self.settled) > self.settled_keep:
            self.settled.popitem(last=False)
        self._count(state)
        self._drop_request(req)

    # -- persistence hooks (federation/store_backed.py) ----------------------
    #
    # The in-process ledger IS the shared state, so these are no-ops. The
    # store-backed subclass persists every request transition to the
    # PartitionState CR through the CAS funnel, and allocates rids from
    # the CR — one protocol implementation, two transports.

    def _alloc_rid(self) -> int:
        return next(self._rid)

    def _persist_request(self, req: ReserveRequest) -> None:
        pass

    def _drop_request(self, req: ReserveRequest) -> None:
        pass

    def _persist_membership_purge(self, pid: int) -> None:
        pass

    def find(self, rid: int) -> Optional[ReserveRequest]:
        with self._lock:
            return self.requests.get(rid) or self.settled.get(rid)

    def _journal_reserve(self, kind: str, **fields) -> None:
        """The reserve/transfer journal funnel: every protocol step is a
        durable control record in the SHARED intent journal, so a
        restarted partition (or a warm standby tailing the stream) sees
        the full cross-partition audit trail. The VT009 witness.

        Each record carries a correlation ``ctx`` stamp
        (obs/lifecycle.py) unless the caller already attached job-level
        stamps — the ``ctx`` key is present only when the timeline store
        is enabled, so the pre-ctx record shape is preserved verbatim
        with the store off."""
        if self.journal is None:
            return
        if "ctx" not in fields and "jobs" not in fields:
            ctx = TIMELINE.stamp(part=fields.get("frm"),
                                 epoch=fields.get("epoch"))
            if ctx is not None:
                fields = dict(fields, ctx=ctx)
        self.journal.record_control(kind, fields)

    # -- requester side ------------------------------------------------------

    def outstanding(self, frm: int) -> Optional[ReserveRequest]:
        with self._lock:
            for req in self.requests.values():
                if req.frm == frm and req.state in _OPEN:
                    return req
        return None

    def pick_donor(self, frm: int) -> Optional[int]:
        """Deterministic donor choice: the other partition with the most
        recently PUBLISHED idle CPU (ties broken toward the lowest pid)
        that can afford to give a node up (keeps at least one unpinned
        node). Published values, not live reads — no partition ever
        inspects another's cache."""
        best: Optional[int] = None
        best_idle = -1.0
        for pid in self.pmap.assignable_pids():
            if pid == frm:
                continue
            if len(self.pmap.unpinned_nodes_of(pid)) <= 1:
                continue
            with self._lock:
                idle = self._idle.get(pid, (0.0, 0.0))[0]
            if idle > best_idle:
                best, best_idle = pid, idle
        return best

    def request(self, frm: int, to: int, cpu: float, mem: float,
                epoch_from: int) -> Optional[int]:
        """Journal a reserve intent from partition ``frm`` to owner
        ``to``; at most one outstanding request per requester. The
        intent is stamped with BOTH partitions' fencing epochs — the
        requester's own and the owner epoch it observed through the
        fencing registry."""
        if to == frm or self.pmap.state_of(to) != "active":
            return None
        if self.outstanding(frm) is not None:
            return None
        now = self.time_fn()
        epoch_to = self.registry.current(to) if self.registry is not None \
            else 0
        rid = self._alloc_rid()
        with self._lock:
            req = ReserveRequest(rid, frm, to, cpu, mem, now,
                                 now + self.timeout_s, epoch_from, epoch_to)
            self.requests[rid] = req
            self._count(REQUESTED)
        self._persist_request(req)
        self._journal_reserve("reserve", rid=rid, frm=frm, to=to, cpu=cpu,
                              mem=mem, epoch_from=epoch_from,
                              epoch_to=epoch_to, deadline=req.deadline)
        return rid

    # -- owner side (cycle boundary) -----------------------------------------

    def review(self, pid: int, epoch: int) -> None:
        """Grant or reject every open request addressed to partition
        ``pid`` — called by the owner's leader at its cycle boundary
        (the scheduler shell's federation hook). ``epoch`` is the
        reviewing leadership's fencing epoch; a deposed leader (epoch
        below the partition's watermark) may not settle anything."""
        if self.registry is not None and epoch < self.registry.current(pid):
            return
        cache = self._caches.get(pid)
        if cache is None:
            return
        with self._lock:
            pending = sorted((r.rid, r) for r in self.requests.values()
                             if r.to == pid and r.state in _OPEN)
        for _, req in pending:
            if req.state == REQUESTED:
                self._start_grant(req, cache, epoch)
            if req.state == GRANTING:
                self._drain_and_transfer(req, cache, epoch)
        self._vacate_pinned(pid, cache)
        if self.donor_guard:
            self._evict_straddlers(pid, cache)

    def _evict_straddlers(self, pid: int, cache) -> None:
        """Membership hygiene (elastic only, with ``donor_guard``): a
        gang that is NOT fully admitted must not straddle a membership
        change. After a queue move its half-bound tasks can sit on
        nodes the new owner does not own — remote usage that still
        counts against the queue's share while the scoped capacity can
        never complete the gang (proportion sees the queue overused,
        allocate binds nothing, the placed tasks' durations never start
        because the gang never re-admits: a permanent deadlock). Evict
        the foreign-placed tasks of unadmitted gangs; the gang re-pends
        whole and binds cleanly inside the new owner's scope."""
        from ..api import TaskStatus
        owned = set(self.pmap.nodes_of(pid))
        for job in sorted(cache.jobs.values(), key=lambda j: j.uid):
            if job.ready():
                continue
            for uid in sorted(job.tasks):
                task = job.tasks[uid]
                if not task.node_name or task.node_name in owned:
                    continue
                if task.status in (TaskStatus.RELEASING,
                                   TaskStatus.PENDING):
                    continue
                try:
                    cache.evict(task, "membership-straddle")
                except Exception:
                    log.exception("straddler evict %s failed; the "
                                  "resync queue owns the retry", uid)

    def _vacate_pinned(self, pid: int, cache) -> None:
        """Evict partition ``pid``'s own straggler tasks off any node
        pinned for an open grant. Queue moves (rebalancer, elastic
        split/merge) can home a RUNNING task in a partition that does
        not own its node — the donor's drain walks only its own mirror,
        so without this sweep a pinned node could transfer while still
        loaded and the receiver would overcommit it. Each partition
        evicts through its OWN journaled+fenced funnel; the donor's
        drain waits for every mirror to empty."""
        from ..api import TaskStatus
        with self._lock:
            pinned = sorted(req.node for req in self.requests.values()
                            if req.state == GRANTING and req.node
                            and req.to != pid)
        for name in pinned:
            node = cache.nodes.get(name)
            if node is None or not node.tasks:
                continue
            for uid in sorted(node.tasks):
                clone = node.tasks[uid]
                job = cache.jobs.get(clone.job)
                task = job.tasks.get(uid) if job is not None else None
                if task is None or task.status == TaskStatus.RELEASING:
                    continue
                try:
                    cache.evict(task, "cross-partition-reserve")
                except Exception:
                    log.exception("pinned-node vacate evict %s failed; "
                                  "the resync queue owns the retry", uid)

    @staticmethod
    def _has_pending_demand(cache) -> bool:
        """True when the donor's own cache holds an unadmitted gang with
        PENDING tasks — the same demand signal ``_starved_need`` reads,
        without the age horizon (OWN state only; never another
        partition's cache)."""
        from ..api import TaskStatus
        for job in cache.jobs.values():
            if job.min_available <= 0 or job.ready():
                continue
            for task in job.tasks.values():
                if task.status == TaskStatus.PENDING:
                    return True
        return False

    def _eligible_nodes(self, pid: int, cache) -> List[str]:
        out = []
        for name in self.pmap.unpinned_nodes_of(pid):
            node = cache.nodes.get(name)
            if node is not None and node.ready:
                out.append(name)
        return out

    def _start_grant(self, req: ReserveRequest, cache, epoch: int) -> None:
        """Phase 2a: choose and pin a donor node, or reject. The donor
        is the owner's least-loaded eligible node that covers the
        request by ALLOCATABLE (capacity follows demand even when the
        node is currently busy — draining empties it), falling back to
        the largest node when none covers it fully. The owner always
        keeps one unpinned node.

        A donor that itself has PENDING demand may only hand over EMPTY
        nodes: draining a busy node evicts running work the donor still
        needs placed, and under systemic overload (everyone starved,
        everyone publishing residual idle) those mutual drains destroy
        bound work faster than it can complete — a cluster-wide
        livelock. An unloaded donor keeps the original capacity-follows-
        demand behavior: its busy nodes drain and transfer."""
        nodes = self._eligible_nodes(req.to, cache)
        if len(nodes) <= 1:
            with self._lock:
                self._settle(req, REJECTED)
            self._journal_reserve("reserve_reject", rid=req.rid,
                                  epoch=epoch, reason="last-node")
            return
        if self.donor_guard and self._has_pending_demand(cache):
            nodes = [n for n in nodes if not cache.nodes[n].tasks]
            if not nodes:
                with self._lock:
                    self._settle(req, REJECTED)
                self._journal_reserve("reserve_reject", rid=req.rid,
                                      epoch=epoch, reason="donor-loaded")
                return
        covering = [n for n in nodes
                    if cache.nodes[n].allocatable.cpu >= req.cpu
                    and cache.nodes[n].allocatable.memory >= req.mem]
        if covering:
            # fewest resident tasks first (cheapest drain), then name
            chosen = min(covering,
                         key=lambda n: (len(cache.nodes[n].tasks), n))
        else:
            # nothing covers the request: hand over the LARGEST node
            # (maximum delivered capacity per transfer — repeated
            # small-node grants would churn without ever fitting the gang)
            chosen = min(nodes,
                         key=lambda n: (-cache.nodes[n].allocatable.cpu,
                                        len(cache.nodes[n].tasks), n))
        with self._lock:
            req.node = chosen
            req.state = GRANTING
        # persist the request transition BEFORE the pin: the pin write's
        # watch echo re-applies the CR's request record onto local
        # mirrors, so the record must already say GRANTING (store-backed
        # transport ordering, federation/store_backed.py)
        self._persist_request(req)
        self.pmap._pin_node_raw(chosen, req.rid)
        self._journal_reserve("reserve_pin", rid=req.rid, node=chosen,
                              epoch=epoch)

    def _drain_and_transfer(self, req: ReserveRequest, cache,
                            epoch: int) -> None:
        """Phase 2b: evict the owner's remaining tasks off the pinned
        node through the owner's OWN journaled+fenced evict funnel, and
        flip ownership once the node is empty. The requester never
        touches the owner's state."""
        from ..api import TaskStatus
        node = cache.nodes.get(req.node)
        if node is None or self.pmap.pin_of(req.node) != req.rid:
            # the donor vanished (node_fail) mid-drain: back to square
            # one; the deadline still bounds the whole exchange
            with self._lock:
                req.node = ""
                req.state = REQUESTED
            self._persist_request(req)
            return
        if node.tasks:
            for uid in sorted(node.tasks):
                clone = node.tasks[uid]
                job = cache.jobs.get(clone.job)
                task = job.tasks.get(uid) if job is not None else None
                if task is None or task.status == TaskStatus.RELEASING:
                    continue
                try:
                    cache.evict(task, "cross-partition-reserve")
                except Exception:
                    log.exception("reserve drain evict %s failed; the "
                                  "resync queue owns the retry", uid)
            if node.tasks:
                return                 # not empty yet: next cycle
        for other in self._caches.values():
            # a task whose queue moved away (rebalancer/elastic) is
            # homed in ANOTHER partition's cache while still placed on
            # this node — that partition's _vacate_pinned sweep evicts
            # it; the transfer must wait for every mirror to drain or
            # the receiver would see a loaded node as empty
            mirror = other.nodes.get(req.node)
            if mirror is not None and mirror.tasks:
                return
        self.pmap._transfer_node_raw(req.node, req.frm)
        with self._lock:
            req.epoch_granted = epoch
            self.node_transfers += 1
            self._settle(req, GRANTED)
        self._journal_reserve("reserve_grant", rid=req.rid, node=req.node,
                              frm=req.to, to=req.frm,
                              epoch_from=req.epoch_from, epoch=epoch)

    # -- timeout-based release (any partition's cycle) -----------------------

    def expire(self, now: Optional[float] = None) -> int:
        """Settle every open request whose deadline passed — run by
        WHICHEVER partition's cycle gets there first, so a killed
        requester or owner can never strand a request (or a pinned,
        half-drained node) forever."""
        now = self.time_fn() if now is None else now
        expired = []
        with self._lock:
            for req in list(self.requests.values()):
                if req.state in _OPEN and now > req.deadline:
                    expired.append(req)
                    self._settle(req, EXPIRED)
        for req in expired:
            if req.node:
                self.pmap._pin_node_raw(req.node, None)
            self._journal_reserve("reserve_expire", rid=req.rid,
                                  node=req.node)
        return len(expired)

    # -- queue rebalancing (the same funnel) ---------------------------------

    def move_queue(self, queue: str, to: int, epoch: int) -> bool:
        """Begin rebalancing ``queue`` to partition ``to``: journal the
        move and mark the queue draining. Ownership flips only in
        ``settle_moves`` once the queue's in-flight intents drained."""
        frm = self.pmap.owner_of_queue(queue)
        if frm is None or frm == to or queue in self.pmap.draining:
            return False
        if self.registry is not None \
                and epoch < self.registry.current(frm):
            return False             # a deposed leader may not move queues
        self._journal_reserve("queue_move", queue=queue, frm=frm, to=to,
                              epoch=epoch)
        self.pmap._begin_drain_raw(queue, to)
        return True

    def _queue_has_open_intents(self, queue: str, cache) -> bool:
        if self.journal is None:
            return False
        for intent in self.journal.unacked():
            job = cache.jobs.get(intent.job)
            if job is not None and job.queue == queue:
                return True
        return False

    def settle_moves(self, pid: int, epoch: int) -> int:
        """Complete every draining queue move whose source is ``pid``:
        once no open journal intent references the queue's jobs, move
        the jobs (and their node mirrors) to the destination partition's
        cache and flip ownership. Returns the number of flips."""
        if self.registry is not None and epoch < self.registry.current(pid):
            return 0                 # deposed-epoch reviewers may not flip
        cache = self._caches.get(pid)
        if cache is None:
            return 0
        moves = [(q, dest) for q, dest in sorted(self.pmap.draining.items())
                 if self.pmap.owner_of_queue(q) == pid]
        flipped = 0
        for queue, dest in moves:
            if self._queue_has_open_intents(queue, cache):
                continue
            dest_cache = self._caches.get(dest)
            if dest_cache is None:
                continue
            moved_jobs = self._move_queue_jobs(queue, cache, dest_cache)
            if moved_jobs is None:
                continue             # mirrors not ready: next cycle
            self.pmap._transfer_queue_raw(queue, dest)
            with self._lock:
                self.queue_moves += 1
            # per-job lifecycle stamps (vlint VT022): each moved job gets
            # its own correlation ctx, recorded locally AND carried
            # inside the single queue_move_done record, so a follower on
            # the destination continues every job's timeline without a
            # duplicate (the store dedupes on (part, eid))
            job_ctx: Dict[str, dict] = {}
            for jid in moved_jobs:
                ctx = TIMELINE.stamp(part=pid, epoch=epoch)
                if ctx is not None:
                    job_ctx[jid] = ctx
                    TIMELINE.record(jid, "move", ctx=ctx, queue=queue,
                                    frm=pid, to=dest)
                    OBS_TRACE.flow_step("queue_move", f"job:{jid}",
                                        queue=queue)
            extra = {"jobs": job_ctx} if job_ctx else {}
            self._journal_reserve("queue_move_done", queue=queue, frm=pid,
                                  to=dest, epoch=epoch, **extra)
            flipped += 1
        return flipped

    @staticmethod
    def _move_queue_jobs(queue: str, frm_cache,
                         to_cache) -> Optional[List[str]]:
        """Surgically move a drained queue's jobs between partition
        caches: the job objects (and their placed tasks' node-mirror
        accounting) leave the source cache — remove_job also purges any
        queued retry/dead-letter state, so no orphaned side effects —
        and land in the destination, dirty-marked on both sides.
        Returns the moved job uids, or ``None`` when the flip deferred.

        The move is all-or-nothing: before touching either cache it
        proves every placed task fits its destination node mirror.
        A mirror that cannot absorb the accounting (a transient skew
        while an eviction or vacate sweep is still in flight) defers
        the whole flip to the next cycle — a half-applied move would
        strand jobs in a cache whose queue it no longer owns."""
        from ..api import TaskStatus
        moved = [j for j in list(frm_cache.jobs.values())
                 if j.queue == queue]
        demand: Dict[str, List] = {}
        for job in moved:
            for task in job.tasks.values():
                if task.node_name and task.status != TaskStatus.PIPELINED:
                    demand.setdefault(task.node_name, []).append(task)
        for node_name, tasks in demand.items():
            node = to_cache.nodes.get(node_name)
            if node is None:
                continue
            headroom = node.idle.clone()
            for task in tasks:
                if task.uid in node.tasks:
                    continue
                if not task.resreq.less_equal(headroom):
                    log.warning(
                        "deferring queue %s move: node %s mirror in the "
                        "destination cannot absorb task %s yet",
                        queue, node_name, task.uid)
                    return None
                headroom.sub(task.resreq)
        for job in moved:
            frm_cache.remove_job(job.uid)
            for task in job.tasks.values():
                node_name = task.node_name
                if node_name and node_name in frm_cache.nodes:
                    frm_cache.mark_node_dirty(node_name)
                    frm_cache.nodes[node_name].remove_task(task)
                    # remove_task clears node_name, but the task is
                    # still PLACED cluster-side — only its cache home
                    # moves; restore it for the destination mirror
                    task.node_name = node_name
            to_cache.add_job(job)
            for task in job.tasks.values():
                node = to_cache.nodes.get(task.node_name) \
                    if task.node_name else None
                if node is not None and task.uid not in node.tasks:
                    to_cache.mark_node_dirty(node.name)
                    node.add_task(task)
        return [job.uid for job in moved]

    # -- elastic membership (the same journaled funnel; vlint VT019) ---------

    def release_nodes(self, pid: int, to: int, epoch: int) -> int:
        """MERGE node drain: hand every unpinned node partition ``pid``
        owns that is EMPTY in its own cache (its resident tasks either
        completed or left with their moved jobs — whose mirrors already
        live in the destination cache) to partition ``to``, through the
        journaled transfer funnel. Nodes still running the retiring
        partition's tasks stay until they drain naturally; pinned nodes
        belong to an open reserve, which ``retire_blockers`` defers on
        anyway. Returns how many nodes ``pid`` still owns."""
        if self.registry is not None \
                and epoch < self.registry.current(pid):
            return len(self.pmap.nodes_of(pid))
        if self.pmap.state_of(to) != "active":
            return len(self.pmap.nodes_of(pid))
        cache = self._caches.get(pid)
        for name in self.pmap.unpinned_nodes_of(pid):
            node = cache.nodes.get(name) if cache is not None else None
            if node is not None and node.tasks:
                continue
            self._journal_reserve("node_handoff", node=name, frm=pid,
                                  to=to, epoch=epoch)
            self.pmap._transfer_node_raw(name, to)
            with self._lock:
                self.node_transfers += 1
        return len(self.pmap.nodes_of(pid))

    def partition_spawn(self, frm: int, epoch: int) -> Optional[int]:
        """SPLIT phase 1: mint a new partition id through the journaled
        membership funnel. ``frm`` is the splitting partition; its
        fencing epoch gates the record (a deposed leader may not grow
        the membership). Store-backed, the mint is one CAS on the
        PartitionState CR — other partitions see the new member or
        don't, never a torn state. The caller (the elastic controller's
        runner hooks) then spawns the scheduler shell + per-partition
        Lease and moves queues via the EXISTING ``move_queue`` funnel,
        so no job is ever schedulable by two partitions at any
        instant."""
        if self.registry is not None \
                and epoch < self.registry.current(frm):
            return None
        pid = self.pmap._spawn_partition_raw()
        self._journal_reserve("partition_spawn", pid=pid, frm=frm,
                              epoch=epoch)
        return pid

    def begin_retire(self, pid: int, epoch: int) -> bool:
        """MERGE phase 1: mark ``pid`` retiring — it keeps scheduling
        what it still owns while its queues drain away through
        ``move_queue``, but can no longer receive ownership, be a
        donor/requester target, or take new registrations. Refuses for
        the last active partition (the membership never empties)."""
        if self.pmap.state_of(pid) != "active":
            return False
        if self.registry is not None \
                and epoch < self.registry.current(pid):
            return False
        if len(self.pmap.assignable_pids()) <= 1:
            return False
        self._journal_reserve("partition_retire_begin", pid=pid,
                              epoch=epoch)
        self.pmap._begin_retire_raw(pid)
        return True

    def retire_blockers(self, pid: int) -> List[str]:
        """What still prevents ``pid`` from retiring — the merge defers
        (returns non-empty) while ANY of these reference the partition:
        owned queues/nodes, draining moves touching it, an OPEN reserve
        naming it as requester or owner (a pin held by a retiring
        partition releases only by grant or deadline expiry — the
        ledger, not the retirement, owns that lifecycle), or an open
        journal intent on a job still homed in its cache."""
        out: List[str] = []
        if self.pmap.queues_of(pid):
            out.append("owned-queues")
        if self.pmap.nodes_of(pid):
            out.append("owned-nodes")
        with self.pmap._lock:
            draining = dict(self.pmap.draining)
        for queue, dest in draining.items():
            if dest == pid:
                out.append("draining-inbound")
                break
        with self._lock:
            for req in self.requests.values():
                if req.state in _OPEN and pid in (req.frm, req.to):
                    out.append("open-reserve")
                    break
        cache = self._caches.get(pid)
        if cache is not None and self.journal is not None:
            for intent in self.journal.unacked():
                if intent.job in cache.jobs:
                    out.append("open-intent")
                    break
        return out

    def partition_retire(self, pid: int, epoch: int) -> bool:
        """MERGE phase 2: retire a fully drained partition. Defers
        (returns False) while ``retire_blockers`` is non-empty — in
        particular an open cross-partition reserve pin held by the
        retiring partition defers retirement until the ledger's
        deadline expiry releases it. On success the membership record
        journals, the pid leaves the map, and every ledger signal the
        partition ever published (idle, load, load_seen freshness,
        cache attachment) is purged so the retired pid can never
        linger as a ghost donor or rebalance target."""
        if self.pmap.state_of(pid) is None:
            return False
        if self.registry is not None \
                and epoch < self.registry.current(pid):
            return False
        if self.retire_blockers(pid):
            return False
        self._journal_reserve("partition_retire", pid=pid, epoch=epoch)
        self.pmap._retire_partition_raw(pid)
        self.purge_partition(pid)
        return True

    def purge_partition(self, pid: int) -> None:
        """Drop every per-partition signal for a retired pid (the ghost
        -partition fix): without this, stale ``load_seen``/``idle``
        entries keep the dead pid a candidate donor and rebalance
        target until freshness expiry."""
        with self._lock:
            self._idle.pop(pid, None)
            self._load.pop(pid, None)
            self._load_seen.pop(pid, None)
            self._caches.pop(pid, None)
        self._persist_membership_purge(pid)

    # -- introspection -------------------------------------------------------

    def detail(self) -> dict:
        with self._lock:
            open_reqs = [r.as_dict() for r in self.requests.values()
                         if r.state in _OPEN]
            return {
                "counts": dict(self.counts),
                "node_transfers": self.node_transfers,
                "queue_moves": self.queue_moves,
                "open": sorted(open_reqs, key=lambda d: d["rid"]),
            }
