"""Elastic partition membership (docs/federation.md; ROADMAP item 4):
the partition COUNT itself becomes load-driven. PR 13's rebalancer
moves queues between a FIXED set of partitions; this controller grows
and shrinks the set through the same journaled funnels, so a cluster
that outgrows its membership splits and one that shrank merges back —
bounded queue depth from 1 partition to N and back with no operator in
the loop.

Every partition's leader runs one :class:`ElasticController` at its
cycle end (driven from :class:`~.member.PartitionMember`, exception-
isolated like the rebalancer):

1. **SPLIT** — a partition whose cycle budget is chronically exhausted
   (the ``volcano_cycle_budget_exhausted_total`` delta stays positive
   with real pending depth for ``hot_cycles`` consecutive steps — the
   rebalancer-style hysteresis) and that owns at least two settled
   queues mints a new partition through the journaled+fenced
   ``partition_spawn`` funnel, asks the host (the sim runner / a real
   deployment's supervisor) to spawn the scheduler shell + per-
   partition Lease/FencingAuthority via ``spawn_fn``, and sheds half
   its queues to the newborn through the EXISTING
   ``move_queue``/``settle_moves`` two-phase funnel — the queue drains
   (NEITHER side schedules it) and flips atomically, so no job is ever
   schedulable by two partitions at any instant. Capacity follows
   demand through the existing cross-partition reserve protocol: the
   newborn's member files starvation reserves and donors drain nodes
   before handover.
2. **MERGE** — a partition that is chronically idle (zero pending depth
   and no open work for ``idle_cycles`` consecutive steps) and is not
   the lowest active pid marks itself retiring via ``begin_retire``
   (persisted, so a crash mid-merge resumes the drain), moves every
   owned queue to the LOWEST assignable partition through the same
   move funnel, releases its emptied node shard through the journaled
   ``release_nodes`` transfer, and retires via ``partition_retire``
   only once no open reserve, draining move, or journal intent
   references it — an open cross-partition pin held by the retiring
   partition defers retirement until the ledger's deadline expiry
   releases it.
3. **guard** — the rebalancer's flap discipline: each executed
   membership change opens a DOUBLING abstention window (capped), and
   queues received mid-run get a settle window before they count
   toward another decision, so oscillating load cannot flap the
   membership.

Crash windows reconcile to either the old or the new membership, never
a torn one: the spawn/retire records are single journal control records
(store-backed: single CAS writes on the PartitionState CR), a spawned-
but-unloaded partition is simply chronically idle and merges itself
back, and a killed retiring partition resumes its drain from the
persisted ``retiring`` state. All inputs are published snapshots + the
injectable clock, so ``sim --elastic`` replays byte-deterministically.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)

DEFAULT_HOT_CYCLES = 6         # consecutive exhausted steps before a split
DEFAULT_IDLE_CYCLES = 12       # consecutive idle steps before a merge
DEFAULT_COOLDOWN_S = 16.0      # first membership-change abstention window
DEFAULT_MAX_COOLDOWN_S = 256.0
DEFAULT_MAX_PARTITIONS = 8


class ElasticController:
    """One partition's slice of the elastic-membership decision."""

    def __init__(self, pid: int, pmap, ledger, cache,
                 epoch_fn: Callable[[], int],
                 time_fn: Callable[[], float] = time.monotonic,
                 exhausted_fn: Optional[Callable[[], int]] = None,
                 spawn_fn: Optional[Callable[[int], None]] = None,
                 retire_fn: Optional[Callable[[int], None]] = None,
                 hot_cycles: int = DEFAULT_HOT_CYCLES,
                 idle_cycles: int = DEFAULT_IDLE_CYCLES,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 max_cooldown_s: float = DEFAULT_MAX_COOLDOWN_S,
                 max_partitions: int = DEFAULT_MAX_PARTITIONS):
        self.pid = pid
        self.pmap = pmap
        self.ledger = ledger
        self.cache = cache
        self.epoch_fn = epoch_fn
        self.time_fn = time_fn
        # reads the shell's cycle-budget exhaustion counter (monotonic);
        # the hot signal is its per-step delta — the PR-15 overload
        # metric IS the split trigger
        self.exhausted_fn = exhausted_fn or (lambda: 0)
        # host hooks: spawn_fn(new_pid) builds the scheduler shell +
        # per-partition Lease/FencingAuthority for a minted partition;
        # retire_fn(pid) tears this partition's shell down after retire
        self.spawn_fn = spawn_fn
        self.retire_fn = retire_fn
        self.hot_cycles = int(hot_cycles)
        self.idle_cycles = int(idle_cycles)
        self.cooldown_s = float(cooldown_s)
        self.max_cooldown_s = float(max_cooldown_s)
        self.max_partitions = int(max_partitions)
        self._exhausted_prev = 0
        self._hot = 0
        self._idle = 0
        # flap guard: executed membership changes open a doubling window
        self._changes = 0
        self._block_until = 0.0
        # settle window: queues that ARRIVED since the last step must
        # drain under this partition before they count toward another
        # membership decision (mirrors the rebalancer's received-queue
        # discipline — a newborn partition must not merge itself back
        # before its first queue even settles)
        self._settle_until = 0.0
        self._owned_prev: set = set()
        self.retiring = False
        self.merge_target: Optional[int] = None
        self.splits = 0
        self.merges = 0
        self.abstentions = 0
        self.refused = 0
        self.last_split: Optional[dict] = None
        self.last_merge: Optional[dict] = None

    # -- load signals --------------------------------------------------------

    def pending_depth(self) -> int:
        """Pending task count over this partition's owned queues (its
        own cache — the split/merge triggers are local observations;
        only the merge TARGET choice reads published state)."""
        from ..api import TaskStatus
        owned = set(self.pmap.queues_of(self.pid))
        total = 0
        for job in self.cache.jobs.values():
            if job.queue in owned:
                total += len(
                    job.task_status_index.get(TaskStatus.PENDING, {}))
        return total

    def _open_work(self) -> bool:
        """Anything that makes 'idle' a lie: jobs still homed here, a
        draining move in or out, or an open reserve naming this pid."""
        owned = set(self.pmap.queues_of(self.pid))
        for job in self.cache.jobs.values():
            if job.queue in owned:
                return True
        with self.pmap._lock:
            draining = dict(self.pmap.draining)
        for queue, dest in draining.items():
            if dest == self.pid or queue in owned:
                return True
        return self.ledger.outstanding(self.pid) is not None

    # -- the decision --------------------------------------------------------

    def step(self, now: Optional[float] = None) -> None:
        """One leader-gated cycle-end pass: update the hysteresis
        counters, then execute at most ONE membership action."""
        from .. import metrics
        now = self.time_fn() if now is None else now
        owned = set(self.pmap.queues_of(self.pid))
        if owned - self._owned_prev:
            self._settle_until = max(self._settle_until,
                                     now + self.cooldown_s)
        self._owned_prev = owned
        state = self.pmap.state_of(self.pid)
        if self.retiring or state == "retiring":
            self.retiring = True
            self._finish_merge(now)
            metrics.set_elastic_detail(self.pid, self.detail())
            return
        exhausted = int(self.exhausted_fn())
        delta, self._exhausted_prev = \
            exhausted - self._exhausted_prev, exhausted
        pending = self.pending_depth()
        if delta > 0 and pending > 0:
            self._hot += 1
            self._idle = 0
        elif pending == 0 and not self._open_work():
            self._hot = 0
            self._idle += 1
        else:
            self._hot = 0
            self._idle = 0
        if now < self._block_until or now < self._settle_until:
            if self._hot >= self.hot_cycles \
                    or self._idle >= self.idle_cycles:
                self.abstentions += 1
            metrics.set_elastic_detail(self.pid, self.detail())
            return
        if self._hot >= self.hot_cycles:
            self._split(now, pending)
        elif self._idle >= self.idle_cycles:
            self._start_merge(now)
        metrics.set_elastic_detail(self.pid, self.detail())

    def _note_change(self, now: float) -> None:
        self._changes += 1
        window = min(self.cooldown_s * (2 ** (self._changes - 1)),
                     self.max_cooldown_s)
        self._block_until = now + window

    def _split(self, now: float, pending: int) -> None:
        """Mint a partition and shed half the owned queues to it. The
        shed set is deterministic: the deepest-first half (ties toward
        queue name), at least one, never the last settled queue."""
        from .. import metrics
        with self.pmap._lock:
            draining = set(self.pmap.draining)
        settled = [q for q in sorted(self.pmap.queues_of(self.pid))
                   if q not in draining]
        if len(settled) < 2 \
                or len(self.pmap.active_pids()) >= self.max_partitions:
            self.abstentions += 1
            return
        epoch = self.epoch_fn()
        new_pid = self.ledger.partition_spawn(self.pid, epoch)
        if new_pid is None:
            self.refused += 1
            metrics.register_partition_split("refused")
            return
        if self.spawn_fn is not None:
            self.spawn_fn(new_pid)
        depths = self._queue_depths(settled)
        ranked = sorted(settled, key=lambda q: (-depths.get(q, 0), q))
        shed = ranked[:len(settled) // 2]
        moved = [q for q in shed
                 if self.ledger.move_queue(q, new_pid, epoch)]
        self._hot = 0
        self._note_change(now)
        self.splits += 1
        self.last_split = {"t": round(now, 6), "new_pid": new_pid,
                           "moved": moved, "pending": pending}
        metrics.register_partition_split("executed")
        log.warning("elastic: partition %d (pending %d, chronic budget "
                    "exhaustion) split -> new partition %d takes %r",
                    self.pid, pending, new_pid, moved)

    def _queue_depths(self, queues) -> dict:
        from ..api import TaskStatus
        depths = {q: 0 for q in queues}
        for job in self.cache.jobs.values():
            if job.queue in depths:
                depths[job.queue] += len(
                    job.task_status_index.get(TaskStatus.PENDING, {}))
        return depths

    def _merge_target_pid(self) -> Optional[int]:
        """The deterministic merge destination: the LOWEST assignable
        pid other than self. The lowest active pid therefore never
        retires (it is everyone's sink), so concurrent merges cannot
        ping-pong queues between two mutually-retiring partitions."""
        pids = [p for p in self.pmap.assignable_pids() if p != self.pid]
        return min(pids) if pids else None

    def _start_merge(self, now: float) -> None:
        from .. import metrics
        target = self._merge_target_pid()
        if target is None or target > self.pid:
            # self is the lowest active pid: it is the sink, never a
            # merger — the membership bottoms out at one partition
            self._idle = 0
            return
        epoch = self.epoch_fn()
        if not self.ledger.begin_retire(self.pid, epoch):
            self.refused += 1
            metrics.register_partition_merge("refused")
            return
        self.retiring = True
        self.merge_target = target
        self._note_change(now)
        self.last_merge = {"t": round(now, 6), "to": target,
                           "state": "draining"}
        metrics.register_partition_merge("begun")
        log.warning("elastic: idle partition %d retiring, draining into "
                    "partition %d", self.pid, target)
        self._finish_merge(now)

    def _finish_merge(self, now: float) -> None:
        """Drive the drain each cycle until retirement lands: push every
        still-owned queue toward the target, release emptied nodes, and
        attempt the journaled retire (which defers while any open
        reserve/intent/move still references this pid)."""
        from .. import metrics
        epoch = self.epoch_fn()
        target = self.merge_target
        if target is None or self.pmap.state_of(target) != "active":
            target = self._merge_target_pid()
            self.merge_target = target
        if target is None:
            return
        with self.pmap._lock:
            draining = set(self.pmap.draining)
        for queue in self.pmap.queues_of(self.pid):
            if queue not in draining:
                self.ledger.move_queue(queue, target, epoch)
        self.ledger.release_nodes(self.pid, target, epoch)
        if self.ledger.partition_retire(self.pid, epoch):
            self.merges += 1
            self.last_merge = {"t": round(now, 6), "to": target,
                               "state": "retired"}
            metrics.register_partition_merge("completed")
            log.warning("elastic: partition %d retired into partition "
                        "%d", self.pid, target)
            if self.retire_fn is not None:
                self.retire_fn(self.pid)

    # -- introspection (vcctl federation elastic-status) ---------------------

    def detail(self) -> dict:
        return {
            "partition": self.pid,
            "retiring": self.retiring,
            "splits": self.splits,
            "merges": self.merges,
            "abstentions": self.abstentions,
            "refused": self.refused,
            "hot_streak": self._hot,
            "idle_streak": self._idle,
            "block_until": round(self._block_until, 3),
            "settle_until": round(self._settle_until, 3),
            "last_split": dict(self.last_split) if self.last_split
            else None,
            "last_merge": dict(self.last_merge) if self.last_merge
            else None,
            "thresholds": {"hot_cycles": self.hot_cycles,
                           "idle_cycles": self.idle_cycles,
                           "max_partitions": self.max_partitions},
        }
