"""PartitionMap: queue/node-shard ownership for the federated control
plane (docs/federation.md).

Each partition owns a disjoint subset of queues (and therefore the jobs
in them — a task is only ever bound by its queue's owner, which is what
makes cross-partition double-binds impossible by construction) and a
disjoint shard of nodes (so partitions never race on capacity either).
Registration is deterministic round-robin in watch-stream order: the
same trace replays to the same map, which keeps ``sim --federated``
byte-deterministic.

Ownership TRANSFER is different from registration: moving a node or a
queue between partitions is a write to cluster state another partition
owns, and must flow through the reserve/transfer funnel
(federation/reserve.py) so it is journaled, epoch-stamped and
drain-safe. The raw mutators below (``_transfer_node_raw``,
``_transfer_queue_raw``, ``_pin_node_raw``, ``_begin_drain_raw``) exist
for that funnel alone — vlint rule VT009 flags any call to them without
a ``_journal_reserve`` witness on the path (docs/static-analysis.md).

MEMBERSHIP is elastic (docs/federation.md membership-change protocol):
partitions can be spawned and retired at runtime through the journaled
``partition_spawn``/``partition_retire`` funnel on the reserve ledger.
The membership raw mutators (``_spawn_partition_raw``,
``_begin_retire_raw``, ``_retire_partition_raw``) exist for that funnel
alone — vlint rule VT019 flags any call without a ``_journal_reserve``
witness on the path. Partition ids are never reused: ``next_pid`` only
grows, so a fencing epoch, journal record or pin that names a pid can
never be confused with a later incarnation.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..api import ClusterInfo


class PartitionMap:
    """Thread-safe ownership map for N partitions. ``version`` bumps on
    every ownership change so consumers (scopes, health detail) can
    cheaply detect staleness."""

    def __init__(self, n_partitions: int):
        if n_partitions < 1:
            raise ValueError("n_partitions must be >= 1")
        self.n = int(n_partitions)
        self._lock = threading.Lock()
        self.queue_owner: Dict[str, int] = {}
        self.node_owner: Dict[str, int] = {}
        # queue -> destination pid while a queue move drains in-flight
        # intents (the two-phase move: neither side schedules the queue
        # until the flip — no orphaned intents, no double-binds)
        self.draining: Dict[str, int] = {}
        # node -> reserve rid while a grant drains the node before the
        # ownership flip; the owner's scope excludes pinned nodes so it
        # cannot refill capacity it is about to hand over
        self.pinned: Dict[str, int] = {}
        # elastic membership: pid -> "active" | "retiring". Static
        # deployments never touch this, so the initial map is exactly
        # {0..n-1: active} and every code path below degenerates to the
        # fixed-N arithmetic (byte-compat with pre-elastic runs).
        self.active: Dict[int, str] = {p: "active" for p in range(self.n)}
        self.next_pid = self.n
        self.version = 0
        self._rr_queue = 0
        self._rr_node = 0

    # -- membership lookups --------------------------------------------------

    def active_pids(self) -> List[int]:
        """Every live partition (including retiring ones still draining)."""
        with self._lock:
            return sorted(self.active)

    def assignable_pids(self) -> List[int]:
        """Partitions that may RECEIVE new ownership (not retiring)."""
        with self._lock:
            return sorted(p for p, s in self.active.items() if s == "active")

    def state_of(self, pid: int) -> Optional[str]:
        with self._lock:
            return self.active.get(pid)

    # -- registration (watch stream; deterministic round-robin) -------------

    def register_queue(self, name: str) -> int:
        """Assign a newly observed queue to a partition (idempotent)."""
        with self._lock:
            if name not in self.queue_owner:
                pids = sorted(p for p, s in self.active.items()
                              if s == "active")
                self.queue_owner[name] = pids[self._rr_queue % len(pids)]
                self._rr_queue += 1
                self.version += 1
            return self.queue_owner[name]

    def register_node(self, name: str) -> int:
        with self._lock:
            if name not in self.node_owner:
                pids = sorted(p for p, s in self.active.items()
                              if s == "active")
                self.node_owner[name] = pids[self._rr_node % len(pids)]
                self._rr_node += 1
                self.version += 1
            return self.node_owner[name]

    def forget_node(self, name: str) -> None:
        """The node left the cluster (node_fail): drop its ownership and
        any pending pin (the reserve ledger's expiry settles the
        request)."""
        with self._lock:
            self.node_owner.pop(name, None)
            self.pinned.pop(name, None)
            self.version += 1

    # -- lookups -------------------------------------------------------------

    def owner_of_queue(self, name: str) -> Optional[int]:
        with self._lock:
            return self.queue_owner.get(name)

    def owner_of_node(self, name: str) -> Optional[int]:
        with self._lock:
            return self.node_owner.get(name)

    def queues_of(self, pid: int) -> List[str]:
        with self._lock:
            return sorted(q for q, p in self.queue_owner.items() if p == pid)

    def nodes_of(self, pid: int) -> List[str]:
        with self._lock:
            return sorted(n for n, p in self.node_owner.items() if p == pid)

    def unpinned_nodes_of(self, pid: int) -> List[str]:
        with self._lock:
            return sorted(n for n, p in self.node_owner.items()
                          if p == pid and n not in self.pinned)

    def pin_of(self, node: str) -> Optional[int]:
        """The reserve rid a node is pinned for, or None — the locked
        read for protocol code (reading ``pinned`` raw would race a
        concurrent pin/unpin in a threaded deployment)."""
        with self._lock:
            return self.pinned.get(node)

    def counts(self) -> Dict[int, Dict[str, int]]:
        with self._lock:
            out = {p: {"queues": 0, "nodes": 0} for p in sorted(self.active)}
            for p in self.queue_owner.values():
                out.setdefault(p, {"queues": 0, "nodes": 0})["queues"] += 1
            for p in self.node_owner.values():
                out.setdefault(p, {"queues": 0, "nodes": 0})["nodes"] += 1
            return out

    # -- ownership transfer: reserve/transfer funnel ONLY (vlint VT009) -----

    def _transfer_node_raw(self, node: str, to: int) -> None:
        """Flip a node's owner. Reserve/transfer funnel only — callers
        must journal the transfer (VT009)."""
        with self._lock:
            self.node_owner[node] = to
            self.pinned.pop(node, None)
            self.version += 1

    def _transfer_queue_raw(self, queue: str, to: int) -> None:
        with self._lock:
            self.queue_owner[queue] = to
            self.draining.pop(queue, None)
            self.version += 1

    def _pin_node_raw(self, node: str, rid: Optional[int]) -> None:
        """Pin (rid) or unpin (None) a node for an in-flight transfer."""
        with self._lock:
            if rid is None:
                self.pinned.pop(node, None)
            else:
                self.pinned[node] = rid
            self.version += 1

    def _begin_drain_raw(self, queue: str, to: int) -> None:
        with self._lock:
            self.draining[queue] = to
            self.version += 1

    # -- elastic membership: spawn/retire funnel ONLY (vlint VT019) ---------

    def _spawn_partition_raw(self) -> int:
        """Mint a new partition id. Membership funnel only — callers
        must journal the spawn (VT019). Pids are never reused."""
        with self._lock:
            pid = self.next_pid
            self.next_pid = pid + 1
            self.active[pid] = "active"
            self.version += 1
            return pid

    def _begin_retire_raw(self, pid: int) -> None:
        """Mark a partition retiring: it keeps scheduling what it still
        owns but can no longer receive queues/nodes or be a registration
        target. Membership funnel only (VT019)."""
        with self._lock:
            if pid in self.active:
                self.active[pid] = "retiring"
                self.version += 1

    def _retire_partition_raw(self, pid: int) -> None:
        """Remove a fully drained partition from the membership.
        Membership funnel only (VT019)."""
        with self._lock:
            self.active.pop(pid, None)
            self.version += 1

    # -- the per-partition scheduler scope -----------------------------------

    def scope(self, ci: ClusterInfo, pid: int) -> ClusterInfo:
        """Filter a cluster snapshot down to what partition ``pid``
        schedules: its owned queues (draining queues excluded — a queue
        mid-move is scheduled by NOBODY until the flip), the jobs in
        those queues, and its owned node shard minus nodes pinned for an
        in-flight transfer. Values are shared, not copied — this is a
        view, built per cycle after ``SchedulerCache.snapshot()``."""
        with self._lock:
            qown = self.queue_owner
            nown = self.node_owner
            draining = self.draining
            pinned = self.pinned
            out = ClusterInfo()
            out.queues = {u: q for u, q in ci.queues.items()
                          if qown.get(u) == pid and u not in draining}
            out.jobs = {u: j for u, j in ci.jobs.items()
                        if qown.get(j.queue) == pid
                        and j.queue not in draining}
            out.nodes = {n: node for n, node in ci.nodes.items()
                         if nown.get(n) == pid and n not in pinned}
            out.namespaces = ci.namespaces
            out.revocable_nodes = {n: node
                                   for n, node in ci.revocable_nodes.items()
                                   if nown.get(n) == pid and n not in pinned}
            out.node_list = list(out.nodes.values())
            if hasattr(ci, "snap_epoch"):
                out.snap_epoch = ci.snap_epoch
            return out
