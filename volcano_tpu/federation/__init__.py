"""Federated control plane: partitioned schedulers with cross-partition
reserve/reclaim (docs/federation.md).

ROADMAP item 5's sharding rung above the PR-7 HA floor: N scheduler
partitions own disjoint queue subsets and node shards of ONE cluster,
each partition run by its own fenced leader (a per-partition Lease +
FencingAuthority — epochs namespaced by partition id), all coordinating
through the shared intent journal and store. Cross-partition work — a
starved queue reclaiming capacity another partition owns — goes through
the two-phase reserve/transfer protocol in :mod:`reserve`; everything
else is partition-local and needs no coordination at all.

- :class:`PartitionMap` — who owns which queues and node shards, plus
  the per-partition snapshot scope the scheduler shell consumes;
- :class:`ReserveLedger` — the journaled reserve → drain → transfer
  protocol with timeout-based release (a killed partition can never
  strand capacity);
- :class:`PartitionMember` — the per-partition glue the scheduler
  shell's cycle hooks drive (review incoming reserves at the cycle
  boundary, detect starvation, publish health);
- :class:`RebalanceController` — load-driven queue rebalancing
  (closes the ROADMAP item 5 remainder): published load signals feed a
  deterministic greedy bin-balancer with hysteresis and a flap guard,
  executing through the SAME journaled move_queue/settle_moves funnel
  operators use;
- :class:`ElasticController` — load-driven membership (ROADMAP item
  4): chronically budget-exhausted partitions SPLIT through the
  journaled ``partition_spawn`` funnel and chronically idle ones MERGE
  back through ``partition_retire``, queue/node ownership flowing
  through the same move/reserve funnels — bounded depth 1→N→1 with no
  operator in the loop.

``sim --federated N`` (volcano_tpu/sim) proves the protocol: partition
kills mid-trace, zero cross-partition double-binds, aggregate
decision-plane equivalence to a single-scheduler oracle on
non-contended traces; ``sim --elastic`` adds kills mid-split and
mid-merge reconciling to a consistent membership.
"""

from .elastic import ElasticController
from .member import PartitionMember
from .partition import PartitionMap
from .rebalance import RebalanceController
from .reserve import ReserveLedger
from .store_backed import (StoreBackedPartitionMap,
                           StoreBackedReserveLedger,
                           StorePartitionBackend)

__all__ = ["ElasticController", "PartitionMap", "PartitionMember",
           "RebalanceController", "ReserveLedger",
           "StoreBackedPartitionMap", "StoreBackedReserveLedger",
           "StorePartitionBackend"]
