"""ObjectStore: the in-process API-server/etcd substitute.

SURVEY.md §5.8: the reference's distributed communication backend IS the
Kubernetes API server — informer watch streams in, REST writes out. The
rebuild collapses that into one process: a thread-safe object store with
watch callbacks (the informer analogue), an admission-hook chain invoked on
create/update (the webhook-manager analogue), and bind/evict entry points
that emulate the kubelet side (pod starts running once bound; evicted pods
are deleted with a condition).

State lives only here — "the store is the checkpoint" (SURVEY.md §5.4):
every component rebuilds its caches from a relist, exactly like informers
resyncing after a restart.

Watch semantics (docs/robustness.md, store failure model): every write
stamps a cluster-monotonic resourceVersion and appends the event to a
bounded per-kind backlog — the etcd watch-cache analogue. A watcher may
register ``since_rv`` to RESUME a torn stream from where it left off;
when the backlog has already trimmed past that version the store raises
:class:`GoneError` (the HTTP 410 the informer contract answers with a
relist). ``list_with_rv`` returns a consistent (objects, rv) snapshot
under one lock window — the relist anchor. Registration is ATOMIC with
its replay: the handler observes every object exactly once (the replay
IS a synthetic ADD of current state, and live events at or below the
registration horizon are deduplicated per watcher), so a cache wired up
while a ``_notify`` is in flight can neither miss pre-registration state
nor double-apply it.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .apis.objects import Command, Job, Pod, PodGroupCR, QueueCR

ADDED = "added"
UPDATED = "updated"
DELETED = "deleted"
# Bookmark events (the k8s WatchBookmark analogue) carry only a
# resourceVersion: an idle resumable watcher keeps its resume point fresh
# so a later resume stays within the backlog window. Delivered only to
# rv-aware watchers (legacy 3-arg handlers never see them).
BOOKMARK = "bookmark"

# Per-kind watch-event backlog depth: resumes reaching further back than
# this answer GoneError (relist). Generous relative to cycle volume — a
# stream torn for one cycle replays; one torn for a whole soak relists.
DEFAULT_WATCH_BACKLOG = 4096


class ConflictError(Exception):
    """Optimistic-concurrency failure: stored resourceVersion moved past
    the one the writer read (HTTP 409 analogue). Carries the observed and
    expected versions so a retry loop can re-read precisely."""

    def __init__(self, kind: str, key: str, observed: int, expected: int):
        super().__init__(
            f"{kind} {key}: conflict — observed resourceVersion "
            f"{observed} != expected {expected}")
        self.kind = kind
        self.key = key
        self.observed = observed
        self.expected = expected


class GoneError(Exception):
    """HTTP 410 Gone analogue: the requested resourceVersion has aged out
    of the watch backlog — the watcher must relist (list_with_rv) and
    re-watch from the fresh snapshot's version."""

    def __init__(self, kind: str, since_rv: int, oldest: int):
        super().__init__(
            f"{kind}: watch from resourceVersion {since_rv} is gone "
            f"(backlog starts after {oldest}); relist required")
        self.kind = kind
        self.since_rv = since_rv
        self.oldest = oldest


class AdmissionError(Exception):
    """Raised by admission hooks to reject a create/update."""


class _Watcher:
    """One registered watch stream: the handler, whether it takes the
    event resourceVersion, and the registration horizon — live events at
    or below the horizon were already covered by the registration (or
    resume) replay and are skipped, which is what makes registration
    during an in-flight ``_notify`` exactly-once."""

    __slots__ = ("handler", "with_rv", "horizon", "alive")

    def __init__(self, handler: Callable, with_rv: bool, horizon: int):
        self.handler = handler
        self.with_rv = with_rv
        self.horizon = horizon
        self.alive = True

    def deliver(self, event: str, obj, old, rv: int) -> None:
        if not self.alive or (rv and rv <= self.horizon):
            return
        if self.with_rv:
            self.handler(event, obj, old, rv)
        elif event != BOOKMARK:
            self.handler(event, obj, old)


class ObjectStore:
    KINDS = ("Pod", "Job", "PodGroup", "Queue", "Command", "PriorityClass",
             "PersistentVolumeClaim", "Lease", "ResourceQuota",
             "PartitionState")

    def __init__(self, watch_backlog: int = DEFAULT_WATCH_BACKLOG):
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, object]] = {k: {} for k in self.KINDS}
        self._watchers: Dict[str, List[_Watcher]] = {k: [] for k in self.KINDS}
        self._admission_hooks: List[Callable] = []
        self._rv = 0
        self.watch_backlog = max(int(watch_backlog), 1)
        # per-kind event backlog: (rv, event, obj, old) in rv order, plus
        # the rv of the newest TRIMMED event (resume below it = Gone)
        self._backlog: Dict[str, "collections.deque"] = {
            k: collections.deque() for k in self.KINDS}
        self._trimmed_rv: Dict[str, int] = {k: 0 for k in self.KINDS}
        # k8s EventRecorder analogue (cache.go:597-641): bounded event log
        self.events: "collections.deque" = collections.deque(maxlen=2000)

    # -- events (EventRecorder analogue) ------------------------------------

    def record_event(self, kind: str, namespace: str, name: str,
                     etype: str, reason: str, message: str) -> None:
        self.events.append({
            "kind": kind, "namespace": namespace, "name": name,
            "type": etype, "reason": reason, "message": message,
            "time": time.time()})

    def events_for(self, kind: str, namespace: str, name: str) -> List[dict]:
        return [e for e in self.events
                if e["kind"] == kind and e["namespace"] == namespace
                and e["name"] == name]

    # -- admission (webhook-manager analogue) -------------------------------

    def register_admission_hook(self, hook: Callable) -> None:
        """hook(operation, kind, obj, old_obj) -> possibly-mutated obj;
        raises AdmissionError to deny."""
        self._admission_hooks.append(hook)

    def _admit(self, operation: str, kind: str, obj, old=None):
        for hook in self._admission_hooks:
            result = hook(operation, kind, obj, old)
            if result is not None:
                obj = result
        return obj

    # -- watch (informer analogue) ------------------------------------------

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def watch(self, kind: str, handler: Callable,
              since_rv: Optional[int] = None,
              with_rv: bool = False) -> _Watcher:
        """Register a watch stream; returns the watcher token (pass to
        ``unwatch`` to cancel — the transport layer's stream handle).

        - ``since_rv=None`` (a fresh informer): existing objects replay
          as ADDED, atomically with registration — the handler observes
          every object exactly once even when a concurrent write's
          ``_notify`` is mid-flight (the registration horizon dedups the
          overlap).
        - ``since_rv=N`` (a resume after a torn stream): backlog events
          with rv > N replay in order; raises :class:`GoneError` when
          the backlog trimmed past N — the caller relists.
        - ``with_rv=True`` handlers are called ``(event, obj, old, rv)``
          and additionally receive BOOKMARK events.
        """
        with self._lock:
            if since_rv is not None and since_rv < self._trimmed_rv[kind]:
                raise GoneError(kind, since_rv, self._trimmed_rv[kind])
            w = _Watcher(handler, with_rv, horizon=self._rv)
            if since_rv is None:
                replay: List[Tuple[int, str, object, object]] = [
                    (0, ADDED, obj, None)
                    for obj in self._objects[kind].values()]
            else:
                replay = [e for e in self._backlog[kind] if e[0] > since_rv]
            self._watchers[kind].append(w)
            # replay UNDER the lock: no write can interleave between the
            # snapshot and the registration, so the stream the handler
            # sees is gapless and duplicate-free by construction
            for rv, event, obj, old in replay:
                if w.with_rv:
                    handler(event, obj, old,
                            rv or getattr(obj.metadata, "resource_version",
                                          0))
                else:
                    handler(event, obj, old)
        return w

    def unwatch(self, kind: str, watcher: _Watcher) -> None:
        with self._lock:
            watcher.alive = False
            if watcher in self._watchers[kind]:
                self._watchers[kind].remove(watcher)

    def emit_bookmarks(self) -> int:
        """Deliver a BOOKMARK carrying the current resourceVersion to
        every rv-aware watcher of every kind (the periodic
        WatchBookmark). Returns the bookmark rv."""
        with self._lock:
            rv = self._rv
            targets = [(k, list(ws)) for k, ws in self._watchers.items()]
        for _kind, watchers in targets:
            for w in watchers:
                if w.with_rv and w.alive:
                    w.handler(BOOKMARK, None, None, rv)
        return rv

    def _record_event(self, kind: str, event: str, obj, old,
                      rv: int) -> None:
        """Caller holds self._lock: append to the resume backlog in rv
        order and trim past the cap."""
        log = self._backlog[kind]
        log.append((rv, event, obj, old))
        while len(log) > self.watch_backlog:
            trimmed = log.popleft()
            self._trimmed_rv[kind] = max(self._trimmed_rv[kind], trimmed[0])

    def _notify(self, kind: str, event: str, obj, old=None,
                rv: int = 0) -> None:
        rv = rv or getattr(obj.metadata, "resource_version", 0)
        for watcher in list(self._watchers[kind]):
            watcher.deliver(event, obj, old, rv)

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj) -> object:
        kind = obj.KIND
        obj = self._admit("CREATE", kind, obj)
        with self._lock:
            key = obj.metadata.key()
            if key in self._objects[kind]:
                raise ValueError(f"{kind} {key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
            self._record_event(kind, ADDED, obj, None, self._rv)
        self._notify(kind, ADDED, obj)
        return obj

    def create_batch(self, objs: Iterable, admit: bool = True) -> List:
        """Create a batch of objects through ONE store write: a single
        lock window covers the existence checks and inserts (an
        apiserver transaction analogue), and watchers are notified once
        per object only after the whole batch committed. All-or-nothing:
        any duplicate key aborts the batch before anything is inserted.
        resourceVersions are minted in batch order under the same lock,
        so the event stream stays rv-monotonic across the batch.

        ``admit=False`` skips the admission-hook chain — for callers
        that already validated the batch through the amortized batch
        validator (webhooks/admission.submit_job_batch), where a
        per-object hook walk would re-pay exactly the per-job store
        reads the batch path exists to avoid."""
        objs = list(objs)
        if admit:
            objs = [self._admit("CREATE", obj.KIND, obj) for obj in objs]
        with self._lock:
            seen = set()
            for obj in objs:
                key = (obj.KIND, obj.metadata.key())
                if key in seen or obj.metadata.key() \
                        in self._objects[obj.KIND]:
                    raise ValueError(
                        f"{obj.KIND} {obj.metadata.key()} already exists")
                seen.add(key)
            for obj in objs:
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._objects[obj.KIND][obj.metadata.key()] = obj
                self._record_event(obj.KIND, ADDED, obj, None, self._rv)
        for obj in objs:
            self._notify(obj.KIND, ADDED, obj)
        return objs

    def update(self, obj, expect_rv=None) -> object:
        """Update; with ``expect_rv`` set, an optimistic-concurrency write
        that fails with :class:`ConflictError` unless the stored object's
        resourceVersion still matches (the k8s resourcelock/Update CAS
        semantics clients rely on for leader election).

        Contract (identical to the native ``vs_put_cas``): ``None`` or a
        negative value = unconditional; ``0`` = create-only (conflict if
        the object exists); ``> 0`` = the object must exist with exactly
        this resourceVersion."""
        kind = obj.KIND
        with self._lock:
            key = obj.metadata.key()
            old = self._objects[kind].get(key)
        obj = self._admit("UPDATE", kind, obj, old)
        with self._lock:
            cur = self._objects[kind].get(key)
            if expect_rv is not None and expect_rv >= 0:
                cur_rv = (cur.metadata.resource_version
                          if cur is not None else 0)
                if cur_rv != expect_rv:
                    raise ConflictError(kind, key, cur_rv, expect_rv)
            old = cur
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
            event = UPDATED if old is not None else ADDED
            self._record_event(kind, event, obj, old, self._rv)
        # creating via the CAS create-only path is an ADD to watchers,
        # matching the native vs_put_cas EV_ADDED on absent keys
        self._notify(kind, event, obj, old)
        return obj

    def update_status(self, obj) -> object:
        """Status subresource: skips admission."""
        kind = obj.KIND
        with self._lock:
            key = obj.metadata.key()
            old = self._objects[kind].get(key)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
            self._record_event(kind, UPDATED, obj, old, self._rv)
        self._notify(kind, UPDATED, obj, old)
        return obj

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            obj = self._objects[kind].pop(f"{namespace}/{name}", None)
            if obj is not None:
                # deletion consumes a resourceVersion too (the etcd
                # delete revision), so resumable watchers can order a
                # DELETED event against the writes around it
                self._rv += 1
                rv = self._rv
                self._record_event(kind, DELETED, obj, None, rv)
        if obj is not None:
            self._notify(kind, DELETED, obj, rv=rv)
            self._cascade_delete(kind, namespace, name)

    def _cascade_delete(self, kind: str, namespace: str, name: str) -> None:
        """Owner-reference garbage collection (the k8s GC analogue): when
        an owner goes away, its dependents follow — e.g. a deleted Job
        takes its PVCs and PodGroup."""
        for dep_kind in self.KINDS:
            with self._lock:
                victims = [
                    o.metadata.name for o in self._objects[dep_kind].values()
                    if o.metadata.namespace == namespace
                    and any(ref.get("kind") == kind
                            and ref.get("name") == name
                            for ref in o.metadata.owner_references)]
            for vname in victims:
                self.delete(dep_kind, namespace, vname)

    def get(self, kind: str, namespace: str, name: str):
        with self._lock:
            return self._objects[kind].get(f"{namespace}/{name}")

    def list(self, kind: str, namespace: Optional[str] = None) -> List:
        with self._lock:
            objs = list(self._objects[kind].values())
        if namespace is None:
            return objs
        return [o for o in objs if o.metadata.namespace == namespace]

    def list_with_rv(self, kind: str,
                     namespace: Optional[str] = None) -> Tuple[List, int]:
        """Consistent LIST: the objects AND the resourceVersion they are
        consistent at, from one lock window — the relist anchor a watcher
        resumes from after a 410 (the informer ListAndWatch contract)."""
        with self._lock:
            objs = list(self._objects[kind].values())
            rv = self._rv
        if namespace is not None:
            objs = [o for o in objs if o.metadata.namespace == namespace]
        return objs, rv

    # -- kubelet emulation ---------------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        """pods/<p>/binding analogue: place + start running. Binds are
        gated on the pod's PodGroup being schedulable — the in-process
        enforcement of the /pods admission webhook (admit_pod.go:139-155):
        a bare pod must not run while its gang is still Pending."""
        from .api import PodGroupPhase
        with self._lock:
            pod: Pod = self._objects["Pod"].get(f"{namespace}/{name}")
            if pod is None:
                raise KeyError(f"pod {namespace}/{name} not found")
            group = pod.metadata.annotations.get(
                "scheduling.k8s.io/group-name", "")
            if group:
                pg = self._objects["PodGroup"].get(f"{namespace}/{group}")
                if pg is not None and \
                        pg.status.phase == PodGroupPhase.PENDING:
                    raise AdmissionError(
                        f"cannot bind pod {namespace}/{name}: podgroup "
                        f"{group} phase is Pending")
            old = _shallow_status_copy(pod)
            pod.status.node_name = node_name
            pod.status.phase = "Running"
            self._rv += 1
            pod.metadata.resource_version = self._rv
            self._record_event("Pod", UPDATED, pod, old, self._rv)
        self.record_event("Pod", namespace, name, "Normal", "Scheduled",
                          f"Successfully assigned {namespace}/{name} "
                          f"to {node_name}")
        self._notify("Pod", UPDATED, pod, old)

    def evict_pod(self, namespace: str, name: str, reason: str) -> None:
        """Eviction analogue: condition + delete (cache.go:146-176)."""
        with self._lock:
            pod: Pod = self._objects["Pod"].get(f"{namespace}/{name}")
            if pod is None:
                return
            pod.status.conditions.append({"type": "Evicted", "reason": reason})
        self.record_event("Pod", namespace, name, "Warning", "Evict",
                          f"Pod is evicted, because of {reason}")
        self.delete("Pod", namespace, name)

    def finish_pod(self, namespace: str, name: str, succeeded: bool = True,
                   exit_code: Optional[int] = None) -> None:
        """Test/e2e helper: complete a running pod (kubelet analogue)."""
        with self._lock:
            pod: Pod = self._objects["Pod"].get(f"{namespace}/{name}")
            if pod is None:
                return
            old = _shallow_status_copy(pod)
            pod.status.phase = "Succeeded" if succeeded else "Failed"
            pod.status.exit_code = (exit_code if exit_code is not None
                                    else (0 if succeeded else 1))
            self._rv += 1
            pod.metadata.resource_version = self._rv
            self._record_event("Pod", UPDATED, pod, old, self._rv)
        self._notify("Pod", UPDATED, pod, old)


def _shallow_status_copy(pod: Pod) -> Pod:
    import copy
    clone = copy.copy(pod)
    clone.status = copy.deepcopy(pod.status)
    return clone
