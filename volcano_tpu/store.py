"""ObjectStore: the in-process API-server/etcd substitute.

SURVEY.md §5.8: the reference's distributed communication backend IS the
Kubernetes API server — informer watch streams in, REST writes out. The
rebuild collapses that into one process: a thread-safe object store with
watch callbacks (the informer analogue), an admission-hook chain invoked on
create/update (the webhook-manager analogue), and bind/evict entry points
that emulate the kubelet side (pod starts running once bound; evicted pods
are deleted with a condition).

State lives only here — "the store is the checkpoint" (SURVEY.md §5.4):
every component rebuilds its caches from a relist, exactly like informers
resyncing after a restart.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from .apis.objects import Command, Job, Pod, PodGroupCR, QueueCR

ADDED = "added"
UPDATED = "updated"
DELETED = "deleted"


class ConflictError(Exception):
    """Optimistic-concurrency failure: stored resourceVersion moved past
    the one the writer read (HTTP 409 analogue)."""


class AdmissionError(Exception):
    """Raised by admission hooks to reject a create/update."""


class ObjectStore:
    KINDS = ("Pod", "Job", "PodGroup", "Queue", "Command", "PriorityClass",
             "PersistentVolumeClaim", "Lease", "ResourceQuota")

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, object]] = {k: {} for k in self.KINDS}
        self._watchers: Dict[str, List[Callable]] = {k: [] for k in self.KINDS}
        self._admission_hooks: List[Callable] = []
        self._rv = 0
        # k8s EventRecorder analogue (cache.go:597-641): bounded event log
        self.events: "collections.deque" = collections.deque(maxlen=2000)

    # -- events (EventRecorder analogue) ------------------------------------

    def record_event(self, kind: str, namespace: str, name: str,
                     etype: str, reason: str, message: str) -> None:
        self.events.append({
            "kind": kind, "namespace": namespace, "name": name,
            "type": etype, "reason": reason, "message": message,
            "time": time.time()})

    def events_for(self, kind: str, namespace: str, name: str) -> List[dict]:
        return [e for e in self.events
                if e["kind"] == kind and e["namespace"] == namespace
                and e["name"] == name]

    # -- admission (webhook-manager analogue) -------------------------------

    def register_admission_hook(self, hook: Callable) -> None:
        """hook(operation, kind, obj, old_obj) -> possibly-mutated obj;
        raises AdmissionError to deny."""
        self._admission_hooks.append(hook)

    def _admit(self, operation: str, kind: str, obj, old=None):
        for hook in self._admission_hooks:
            result = hook(operation, kind, obj, old)
            if result is not None:
                obj = result
        return obj

    # -- watch (informer analogue) ------------------------------------------

    def watch(self, kind: str, handler: Callable[[str, object, Optional[object]], None]) -> None:
        """handler(event, obj, old_obj); existing objects replay as ADDED."""
        with self._lock:
            self._watchers[kind].append(handler)
            existing = list(self._objects[kind].values())
        for obj in existing:
            handler(ADDED, obj, None)

    def _notify(self, kind: str, event: str, obj, old=None) -> None:
        for handler in list(self._watchers[kind]):
            handler(event, obj, old)

    # -- CRUD ---------------------------------------------------------------

    def create(self, obj) -> object:
        kind = obj.KIND
        obj = self._admit("CREATE", kind, obj)
        with self._lock:
            key = obj.metadata.key()
            if key in self._objects[kind]:
                raise ValueError(f"{kind} {key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
        self._notify(kind, ADDED, obj)
        return obj

    def create_batch(self, objs: Iterable, admit: bool = True) -> List:
        """Create a batch of objects through ONE store write: a single
        lock window covers the existence checks and inserts (an
        apiserver transaction analogue), and watchers are notified once
        per object only after the whole batch committed. All-or-nothing:
        any duplicate key aborts the batch before anything is inserted.

        ``admit=False`` skips the admission-hook chain — for callers
        that already validated the batch through the amortized batch
        validator (webhooks/admission.submit_job_batch), where a
        per-object hook walk would re-pay exactly the per-job store
        reads the batch path exists to avoid."""
        objs = list(objs)
        if admit:
            objs = [self._admit("CREATE", obj.KIND, obj) for obj in objs]
        with self._lock:
            seen = set()
            for obj in objs:
                key = (obj.KIND, obj.metadata.key())
                if key in seen or obj.metadata.key() \
                        in self._objects[obj.KIND]:
                    raise ValueError(
                        f"{obj.KIND} {obj.metadata.key()} already exists")
                seen.add(key)
            for obj in objs:
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._objects[obj.KIND][obj.metadata.key()] = obj
        for obj in objs:
            self._notify(obj.KIND, ADDED, obj)
        return objs

    def update(self, obj, expect_rv=None) -> object:
        """Update; with ``expect_rv`` set, an optimistic-concurrency write
        that fails with :class:`ConflictError` unless the stored object's
        resourceVersion still matches (the k8s resourcelock/Update CAS
        semantics clients rely on for leader election).

        Contract (identical to the native ``vs_put_cas``): ``None`` or a
        negative value = unconditional; ``0`` = create-only (conflict if
        the object exists); ``> 0`` = the object must exist with exactly
        this resourceVersion."""
        kind = obj.KIND
        with self._lock:
            key = obj.metadata.key()
            old = self._objects[kind].get(key)
        obj = self._admit("UPDATE", kind, obj, old)
        with self._lock:
            cur = self._objects[kind].get(key)
            if expect_rv is not None and expect_rv >= 0:
                cur_rv = (cur.metadata.resource_version
                          if cur is not None else 0)
                if cur_rv != expect_rv:
                    raise ConflictError(
                        f"{kind} {key}: resourceVersion {cur_rv} != "
                        f"expected {expect_rv}")
            old = cur
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
        # creating via the CAS create-only path is an ADD to watchers,
        # matching the native vs_put_cas EV_ADDED on absent keys
        self._notify(kind, UPDATED if old is not None else ADDED, obj, old)
        return obj

    def update_status(self, obj) -> object:
        """Status subresource: skips admission."""
        kind = obj.KIND
        with self._lock:
            key = obj.metadata.key()
            old = self._objects[kind].get(key)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[kind][key] = obj
        self._notify(kind, UPDATED, obj, old)
        return obj

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            obj = self._objects[kind].pop(f"{namespace}/{name}", None)
        if obj is not None:
            self._notify(kind, DELETED, obj)
            self._cascade_delete(kind, namespace, name)

    def _cascade_delete(self, kind: str, namespace: str, name: str) -> None:
        """Owner-reference garbage collection (the k8s GC analogue): when
        an owner goes away, its dependents follow — e.g. a deleted Job
        takes its PVCs and PodGroup."""
        for dep_kind in self.KINDS:
            with self._lock:
                victims = [
                    o.metadata.name for o in self._objects[dep_kind].values()
                    if o.metadata.namespace == namespace
                    and any(ref.get("kind") == kind
                            and ref.get("name") == name
                            for ref in o.metadata.owner_references)]
            for vname in victims:
                self.delete(dep_kind, namespace, vname)

    def get(self, kind: str, namespace: str, name: str):
        with self._lock:
            return self._objects[kind].get(f"{namespace}/{name}")

    def list(self, kind: str, namespace: Optional[str] = None) -> List:
        with self._lock:
            objs = list(self._objects[kind].values())
        if namespace is None:
            return objs
        return [o for o in objs if o.metadata.namespace == namespace]

    # -- kubelet emulation ---------------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        """pods/<p>/binding analogue: place + start running. Binds are
        gated on the pod's PodGroup being schedulable — the in-process
        enforcement of the /pods admission webhook (admit_pod.go:139-155):
        a bare pod must not run while its gang is still Pending."""
        from .api import PodGroupPhase
        with self._lock:
            pod: Pod = self._objects["Pod"].get(f"{namespace}/{name}")
            if pod is None:
                raise KeyError(f"pod {namespace}/{name} not found")
            group = pod.metadata.annotations.get(
                "scheduling.k8s.io/group-name", "")
            if group:
                pg = self._objects["PodGroup"].get(f"{namespace}/{group}")
                if pg is not None and \
                        pg.status.phase == PodGroupPhase.PENDING:
                    raise AdmissionError(
                        f"cannot bind pod {namespace}/{name}: podgroup "
                        f"{group} phase is Pending")
            old = _shallow_status_copy(pod)
            pod.status.node_name = node_name
            pod.status.phase = "Running"
            self._rv += 1
            pod.metadata.resource_version = self._rv
        self.record_event("Pod", namespace, name, "Normal", "Scheduled",
                          f"Successfully assigned {namespace}/{name} "
                          f"to {node_name}")
        self._notify("Pod", UPDATED, pod, old)

    def evict_pod(self, namespace: str, name: str, reason: str) -> None:
        """Eviction analogue: condition + delete (cache.go:146-176)."""
        with self._lock:
            pod: Pod = self._objects["Pod"].get(f"{namespace}/{name}")
            if pod is None:
                return
            pod.status.conditions.append({"type": "Evicted", "reason": reason})
        self.record_event("Pod", namespace, name, "Warning", "Evict",
                          f"Pod is evicted, because of {reason}")
        self.delete("Pod", namespace, name)

    def finish_pod(self, namespace: str, name: str, succeeded: bool = True,
                   exit_code: Optional[int] = None) -> None:
        """Test/e2e helper: complete a running pod (kubelet analogue)."""
        with self._lock:
            pod: Pod = self._objects["Pod"].get(f"{namespace}/{name}")
            if pod is None:
                return
            old = _shallow_status_copy(pod)
            pod.status.phase = "Succeeded" if succeeded else "Failed"
            pod.status.exit_code = (exit_code if exit_code is not None
                                    else (0 if succeeded else 1))
            self._rv += 1
        self._notify("Pod", UPDATED, pod, old)


def _shallow_status_copy(pod: Pod) -> Pod:
    import copy
    clone = copy.copy(pod)
    clone.status = copy.deepcopy(pod.status)
    return clone
