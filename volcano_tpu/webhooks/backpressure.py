"""Admission backpressure: the bounded pending-work budget of the
front door (docs/robustness.md overload failure model).

The batched admission path (``submit_job_batch``) accepts work as fast
as clients can POST it; under sustained overload the accepted-but-
unscheduled backlog is what grows without bound — cache memory, snapshot
cost, solve cost all scale with it. ``AdmissionBudget`` bounds it at the
door, the only place the system can still say no cheaply:

- **per-queue depth**: each queue may carry at most ``max_queue_depth``
  accepted-but-unscheduled tasks;
- **global bytes**: the whole pending set may cost at most
  ``max_total_bytes`` (estimated — see ``estimate_job_bytes``);
- **priority-aware shedding**: past the ``shed_watermark`` fill
  fraction a priority floor rises linearly with fill, so the LOWEST
  priority batches are rejected first and high-priority work still
  lands right up to the hard limit;
- **retry-after hints**: every refusal carries ``retry_after_s``
  derived from the observed drain throughput (an EWMA the scheduler
  feeds with per-cycle bind counts), so well-behaved clients back off
  proportionally to the actual excess instead of hammering.

Refusals are a typed :class:`BackpressureError` (the 429 of this
in-process apiserver; it subclasses ``AdmissionError`` so existing
callers that catch admission rejections keep working) and are counted
in ``volcano_admission_backpressure_total{reason}``.

Accounting contract: ``admit_batch``/``charge`` at acceptance,
``credit`` when the work leaves the pending set (bound or deleted) —
the scheduler/sim feeds ``observe_drain`` so the retry hints track real
throughput. All timestamps ride the injectable ``time_fn``; the seeded
``chaos.OverloadInjector`` drives the budget deterministically in the
overload soaks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..store import AdmissionError

DEFAULT_MAX_QUEUE_DEPTH = 10_000
DEFAULT_MAX_TOTAL_BYTES = 256 * (1 << 20)
DEFAULT_SHED_WATERMARK = 0.75
# interpolation ceiling for the shed floor: queue fill rising from the
# watermark to 1.0 raises the floor 0 -> PRIORITY_CEIL, so priority-10
# work still lands until the queue is genuinely full
PRIORITY_CEIL = 10
# retry hints are capped: with no observed throughput yet the raw
# excess/throughput quotient is unbounded, and an unbounded hint parks
# clients forever on a system that is about to recover
MAX_RETRY_AFTER_CYCLES = 64

# byte-estimate model for a Job CR: metadata + spec overhead plus a
# per-task envelope (pod template, resources, policies) — deliberately
# coarse; the budget bounds growth, it does not meter heap bytes
_JOB_OVERHEAD_B = 512
_TASK_OVERHEAD_B = 256


class BackpressureError(AdmissionError):
    """Typed 429: the bounded pending-work budget refused the
    submission. ``reason`` is ``queue_depth`` | ``bytes`` |
    ``priority_shed``; ``retry_after_s`` is the drain-derived hint."""

    def __init__(self, message: str, reason: str, queue: str = "",
                 retry_after_s: float = 0.0,
                 priority_floor: Optional[int] = None):
        super().__init__(message)
        self.reason = reason
        self.queue = queue
        self.retry_after_s = float(retry_after_s)
        self.priority_floor = priority_floor


def estimate_job_bytes(n_tasks: int) -> int:
    """The budget's coarse cost model for one job of ``n_tasks``."""
    return _JOB_OVERHEAD_B + _TASK_OVERHEAD_B * int(n_tasks)


class AdmissionBudget:
    """Thread-safe pending-work ledger for the admission front door."""

    def __init__(self, max_queue_depth: int = DEFAULT_MAX_QUEUE_DEPTH,
                 max_total_bytes: int = DEFAULT_MAX_TOTAL_BYTES,
                 shed_watermark: float = DEFAULT_SHED_WATERMARK,
                 cycle_period_s: float = 1.0,
                 time_fn=time.monotonic):
        if not 0.0 <= shed_watermark <= 1.0:
            raise ValueError(f"shed_watermark {shed_watermark} not in "
                             f"[0, 1]")
        self.max_queue_depth = int(max_queue_depth)
        self.max_total_bytes = float(max_total_bytes)
        self.shed_watermark = float(shed_watermark)
        self.cycle_period_s = float(cycle_period_s)
        self.time_fn = time_fn
        self._lock = threading.Lock()
        self.depth: Dict[str, int] = {}       # queue -> pending tasks
        self.total_bytes = 0.0
        self.high_water_depth = 0
        self.high_water: Dict[str, int] = {}  # per-queue depth peaks
        self.shed: Dict[str, int] = {}        # reason -> refusals
        self.admitted = 0
        # EWMA of drained tasks/second (the scheduler's bind feedback);
        # 0.0 = never observed — retry hints then price one excess task
        # at one cycle period (the most conservative deterministic guess)
        self.drain_rate = 0.0

    # -- observation ---------------------------------------------------------

    def observe_drain(self, tasks: int, dt_s: Optional[float] = None
                      ) -> None:
        """Feed the drain-throughput EWMA: ``tasks`` left the pending
        set over ``dt_s`` seconds (default: one cycle period)."""
        dt = self.cycle_period_s if dt_s is None else max(dt_s, 1e-9)
        rate = tasks / dt
        with self._lock:
            self.drain_rate = rate if self.drain_rate == 0.0 \
                else 0.8 * self.drain_rate + 0.2 * rate

    def retry_after_s(self, excess_tasks: float) -> float:
        """The 429 hint: how long until ``excess_tasks`` of headroom
        should exist at the observed drain rate. Monotone non-decreasing
        in the excess (tested), capped at MAX_RETRY_AFTER_CYCLES
        periods."""
        with self._lock:
            return self.retry_after_locked(excess_tasks)

    def _priority_floor_locked(self, queue: str) -> int:
        """Caller holds self._lock: the minimum priority the queue
        accepts at its CURRENT fill (the batch that crosses the
        watermark still lands; what follows meets the floor). 0 below
        the shed watermark; rises linearly to PRIORITY_CEIL at the hard
        limit — lowest-priority batches shed first."""
        if self.max_queue_depth <= 0:
            return 0
        fill = self.depth.get(queue, 0) / float(self.max_queue_depth)
        if fill <= self.shed_watermark:
            return 0
        span = max(1.0 - self.shed_watermark, 1e-9)
        frac = min((fill - self.shed_watermark) / span, 1.0)
        return int(frac * PRIORITY_CEIL + 0.999999)   # ceil, floor<=10

    # -- the gate ------------------------------------------------------------

    def admit_batch(self, per_queue: Dict[str, int], nbytes: float,
                    priority=0) -> None:
        """All-or-nothing budget check + charge for one validated batch:
        ``per_queue`` maps queue name -> task count. Raises
        :class:`BackpressureError` (charging nothing) when any queue
        would exceed its depth, the global byte budget would overflow,
        or the batch's priority is below a shedding queue's floor.

        ``priority`` may be an int or a ZERO-ARG CALLABLE resolved only
        if a non-zero floor is actually hit — the front door passes a
        thunk so the PriorityClass store read is skipped in the common
        unloaded case, and the floor check resolves it under THIS lock
        (no window where a queue crosses the watermark between an
        outside peek and the gate)."""
        from .. import metrics
        resolved: Optional[int] = None if callable(priority) \
            else int(priority)
        with self._lock:
            for queue in sorted(per_queue):
                tasks = per_queue[queue]
                depth = self.depth.get(queue, 0)
                if depth + tasks > self.max_queue_depth > 0:
                    excess = depth + tasks - self.max_queue_depth
                    err = BackpressureError(
                        f"queue {queue!r} pending depth {depth}+{tasks} "
                        f"exceeds {self.max_queue_depth}; retry after "
                        f"{self.retry_after_locked(excess):.1f}s",
                        reason="queue_depth", queue=queue,
                        retry_after_s=self.retry_after_locked(excess))
                    self.shed["queue_depth"] = \
                        self.shed.get("queue_depth", 0) + 1
                    break
                floor = self._priority_floor_locked(queue)
                if floor > 0 and resolved is None:
                    resolved = int(priority())
                if floor > 0 and resolved < floor:
                    err = BackpressureError(
                        f"queue {queue!r} is shedding below priority "
                        f"{floor} (fill past the "
                        f"{self.shed_watermark:.0%} watermark); batch "
                        f"priority {resolved} refused",
                        reason="priority_shed", queue=queue,
                        retry_after_s=self.retry_after_locked(tasks),
                        priority_floor=floor)
                    self.shed["priority_shed"] = \
                        self.shed.get("priority_shed", 0) + 1
                    break
            else:
                if self.total_bytes + nbytes > self.max_total_bytes > 0:
                    err = BackpressureError(
                        f"pending-work bytes "
                        f"{self.total_bytes + nbytes:.0f} exceed the "
                        f"{self.max_total_bytes:.0f} budget",
                        reason="bytes",
                        retry_after_s=self.retry_after_locked(
                            sum(per_queue.values())))
                    self.shed["bytes"] = self.shed.get("bytes", 0) + 1
                else:
                    for queue, tasks in per_queue.items():
                        self.depth[queue] = \
                            self.depth.get(queue, 0) + tasks
                        self.high_water[queue] = max(
                            self.high_water.get(queue, 0),
                            self.depth[queue])
                    self.total_bytes += nbytes
                    self.admitted += 1
                    self.high_water_depth = max(
                        self.high_water_depth,
                        sum(self.depth.values()))
                    self._publish_locked()
                    err = None
        if err is not None:
            metrics.register_backpressure(err.reason)
            raise err

    def retry_after_locked(self, excess_tasks: float) -> float:
        """Caller holds self._lock (the lock is not reentrant, so
        admit_batch cannot call the public form)."""
        rate = self.drain_rate
        per_task = (1.0 / rate) if rate > 0 else self.cycle_period_s
        hint = self.cycle_period_s + max(excess_tasks, 0.0) * per_task
        return min(hint, MAX_RETRY_AFTER_CYCLES * self.cycle_period_s)

    def credit(self, queue: str, tasks: int, nbytes: float = 0.0) -> None:
        """Work left the pending set (bound, completed while pending, or
        deleted): release its budget."""
        with self._lock:
            left = self.depth.get(queue, 0) - tasks
            if left > 0:
                self.depth[queue] = left
            else:
                self.depth.pop(queue, None)
            self.total_bytes = max(self.total_bytes - nbytes, 0.0)
            self._publish_locked()

    def _publish_locked(self) -> None:
        """Caller holds self._lock: gauge publication happens INSIDE
        the mutating critical section so concurrent charge/credit pairs
        cannot publish their snapshots out of order (the metrics module
        takes only its own internal lock — no ordering cycle)."""
        from .. import metrics
        metrics.set_admission_pending(sum(self.depth.values()),
                                      self.total_bytes)

    # -- introspection -------------------------------------------------------

    def pending_depth(self) -> int:
        with self._lock:
            return sum(self.depth.values())

    def detail(self) -> dict:
        with self._lock:
            return {
                "max_queue_depth": self.max_queue_depth,
                "max_total_bytes": self.max_total_bytes,
                "depth": dict(sorted(self.depth.items())),
                "total_bytes": round(self.total_bytes, 1),
                "high_water_depth": self.high_water_depth,
                "high_water": dict(sorted(self.high_water.items())),
                "shed": dict(sorted(self.shed.items())),
                "admitted_batches": self.admitted,
                "drain_rate": round(self.drain_rate, 6),
            }
