"""Admission webhook implementations.

- jobs/mutate    /root/reference/pkg/webhooks/admission/jobs/mutate/
                 mutate_job.go:100-170 — defaults: queue, scheduler name,
                 maxRetry, minAvailable=Σreplicas, task names.
- jobs/validate  admission/jobs/validate/admit_job.go:46-330 — task name and
                 replica consistency, minAvailable bounds, policy legality,
                 queue existence/state.
- queues         admission/queues/{validate,mutate} — weight bounds, state
                 legality; defaults weight=1, reclaimable.
- pods           admission/pods/admit_pod.go:1-203 — gate bare-pod binding
                 on its PodGroup being schedulable.
- podgroups      admission/podgroups/mutate_podgroup.go — default queue.
"""

from __future__ import annotations

import re
from typing import Optional

from ..api import BusAction, BusEvent, QueueState
from ..api.queue_info import (KUBE_HIERARCHY_ANNOTATION_KEY,
                              KUBE_HIERARCHY_WEIGHT_ANNOTATION_KEY)
from ..apis.objects import Job, PodGroupCR, QueueCR
from ..store import AdmissionError, ObjectStore
from .router import AdmissionService, Router, deny

DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

# policy legality table (admit_job.go checkPolicyDuplicate/validatePolicies)
_VALID_JOB_ACTIONS = set(BusAction)
_VALID_EVENTS = set(BusEvent)


def mutate_job(operation: str, job: Job, old) -> Job:
    """Defaulting patch (mutate_job.go:100-170)."""
    if not job.spec.queue:
        job.spec.queue = "default"
    if not job.spec.scheduler_name:
        job.spec.scheduler_name = "volcano"
    if job.spec.max_retry == 0:
        job.spec.max_retry = 3
    for i, task in enumerate(job.spec.tasks):
        if not task.name:
            task.name = f"default{i}"
    if job.spec.min_available == 0:
        job.spec.min_available = sum(t.replicas for t in job.spec.tasks)
    return job


def _validate_job_with_queues(job: Job, queue_of) -> None:
    """The job validation body (admit_job.go:46-330), with the queue
    lookup injected: the single-job webhook passes a store getter, the
    batched front door passes a dict prefetched ONCE per batch — the
    store-read amortization that keeps high-QPS intake from scaling
    admission cost with batch size (docs/federation.md)."""
    if not job.spec.tasks:
        deny("No task specified in job spec")
    total_replicas = 0
    names = set()
    for task in job.spec.tasks:
        if task.replicas < 0:
            deny(f"'replicas' < 0 in task: {task.name}")
        if task.min_available is not None:
            if task.min_available > task.replicas:
                deny(f"'minAvailable' is greater than 'replicas' in task: "
                     f"{task.name}")
        total_replicas += task.replicas
        if task.name in names:
            deny(f"duplicated task name {task.name}")
        if not DNS1123.match(task.name):
            deny(f"task name {task.name} is not a valid DNS-1123 label")
        names.add(task.name)
        _validate_policies(task.policies)
    if job.spec.min_available > total_replicas:
        deny("job 'minAvailable' should not be greater than total replicas "
             "in tasks")
    if job.spec.min_available < 0:
        deny("job 'minAvailable' must be >= 0")
    _validate_policies(job.spec.policies)
    queue: QueueCR = queue_of(job.spec.queue)
    if queue is None:
        deny(f"unable to find job queue: {job.spec.queue}")
    elif queue.status.state != QueueState.OPEN:
        deny(f"can only submit job to queue with state `Open`, "
             f"queue `{queue.metadata.name}` status is "
             f"`{queue.status.state.value}`")


def make_validate_job(store: ObjectStore):
    def validate_job(operation: str, job: Job, old) -> None:
        _validate_job_with_queues(
            job, lambda name: store.get("Queue", "default", name))

    return validate_job


def submit_job_batch(store: ObjectStore, jobs, budget=None,
                     priority_fn=None) -> list:
    """Batched job submission — the high-QPS front door
    (docs/federation.md): the whole batch is defaulted and validated
    against ONE prefetched queue read, then lands through ONE store
    write (``ObjectStore.create_batch``: one lock window, one watcher
    flush), instead of a store read + write + admission walk per job.

    Validation is all-or-nothing: any invalid job rejects the whole
    batch BEFORE anything is written, so a partially-admitted batch can
    never exist (same atomicity a transactional apiserver POST would
    give). Returns the created Job objects; raises AdmissionError with
    the first offending job named.

    ``budget`` (an :class:`webhooks.backpressure.AdmissionBudget`)
    gates the VALIDATED batch against the bounded pending-work budget
    (docs/robustness.md overload failure model): over-depth/over-bytes
    batches — and, past the shed watermark, low-priority ones — are
    refused with a typed ``BackpressureError`` carrying a
    ``retry_after_s`` hint derived from observed drain throughput,
    before anything is written. The batch's priority is the MINIMUM
    across its jobs (``priority_fn(job) -> int``; default resolves the
    job's PriorityClass through one prefetched store read), so a batch
    is only as shed-resistant as its least-deserving member."""
    from .. import metrics
    from .backpressure import estimate_job_bytes
    jobs = list(jobs)
    if not jobs:
        return []
    queues = {q.metadata.name: q for q in store.list("Queue")}
    prepared = []
    per_queue: dict = {}
    nbytes = 0.0
    for job in jobs:
        job = mutate_job("CREATE", job, None)
        try:
            _validate_job_with_queues(job, queues.get)
        except AdmissionError as exc:
            raise AdmissionError(
                f"batch rejected at job "
                f"{job.metadata.namespace}/{job.metadata.name}: {exc}"
            ) from None
        prepared.append(job)
        if budget is not None:
            tasks = sum(t.replicas for t in job.spec.tasks)
            per_queue[job.spec.queue] = \
                per_queue.get(job.spec.queue, 0) + tasks
            nbytes += estimate_job_bytes(tasks)
    if budget is not None:
        # the batch's priority only matters once a target queue is in
        # the shed band — below the watermark the floor is 0 by
        # construction. Passing a THUNK lets the gate resolve it under
        # its own lock exactly when a non-zero floor is hit: the common
        # unloaded case skips the PriorityClass store read entirely,
        # and a queue crossing the watermark concurrently cannot race a
        # stale outside peek (the floor and the priority resolve under
        # one lock).
        def batch_priority() -> int:
            resolve = priority_fn
            if resolve is None:
                classes = {pc.metadata.name: pc.value
                           for pc in store.list("PriorityClass")}

                def resolve(job, _classes=classes):
                    return _classes.get(job.spec.priority_class_name, 0)
            return min(int(resolve(j)) for j in prepared)

        # the backpressure gate: raises BackpressureError (nothing
        # written, nothing charged) or charges the whole batch
        budget.admit_batch(per_queue, nbytes, batch_priority)
    try:
        created = store.create_batch(prepared, admit=False)
    except BaseException:
        # the store refused the batch AFTER the budget charged it
        # (duplicate key, store fault): nothing was written, so the
        # charge must not outlive the call — a leaked charge would
        # ratchet the pending depth up on every failed submit until
        # the queue sheds everything forever
        if budget is not None:
            for ix, queue in enumerate(sorted(per_queue)):
                budget.credit(queue, per_queue[queue],
                              nbytes if ix == 0 else 0.0)
        raise
    metrics.observe_admission_batch(len(created))
    return created


def _validate_policies(policies) -> None:
    events = set()
    exit_codes = set()
    for policy in policies:
        if policy.action not in _VALID_JOB_ACTIONS:
            deny(f"invalid policy action {policy.action}")
        # event and exitCode clauses are mutually exclusive, and a policy
        # must carry one of them (validate/util.go:60-66)
        if policy.event is not None and policy.exit_code is not None:
            deny("must not specify event and exitCode simultaneously")
        if policy.event is None and policy.exit_code is None:
            deny("either event and exitCode should be specified")
        if policy.event is not None:
            if policy.event in events:
                deny(f"duplicate policy event {policy.event}")
            events.add(policy.event)
            if policy.event not in _VALID_EVENTS:
                deny(f"invalid policy event {policy.event}")
        if policy.exit_code is not None:
            if policy.exit_code == 0:
                deny("0 is not a valid error code")
            if policy.exit_code in exit_codes:
                deny(f"duplicate exitCode {policy.exit_code}")
            exit_codes.add(policy.exit_code)


def mutate_queue(operation: str, queue: QueueCR, old) -> QueueCR:
    if queue.spec.weight == 0:
        queue.spec.weight = 1
    return queue


def make_validate_queue(store: ObjectStore):
    def validate_queue(operation: str, queue: QueueCR, old) -> None:
        if operation == "DELETE":
            # validate_queue.go:199-215: the default queue is undeletable,
            # and only Closed queues may be deleted. k8s sends the object
            # being deleted as OldObject.
            target = old if old is not None else queue
            if target.metadata.name == "default":
                deny("`default` queue can not be deleted")
            live = store.get("Queue", target.metadata.namespace,
                             target.metadata.name) or target
            if live.status.state != QueueState.CLOSED:
                deny(f"only queue with state `Closed` can be deleted, "
                     f"queue `{live.metadata.name}` state is "
                     f"`{live.status.state.value}`")
            return
        if queue.spec.weight < 1:
            deny(f"queue weight must be a positive integer, got "
                 f"{queue.spec.weight}")
        if operation == "CREATE" and queue.status.state not in (
                QueueState.OPEN, QueueState.CLOSED):
            deny(f"queue state must be in [Open, Closed], got "
                 f"{queue.status.state.value}")
        _validate_hierarchy(store, queue)
    return validate_queue


def _validate_hierarchy(store: ObjectStore, queue: QueueCR) -> None:
    """Hierarchy annotation legality (validate_queue.go:113-168): path and
    weights lengths match, weights are positive numbers, and no queue may
    sit on another queue's sub path."""
    ann = queue.metadata.annotations
    hierarchy = ann.get(KUBE_HIERARCHY_ANNOTATION_KEY, "")
    weights = ann.get(KUBE_HIERARCHY_WEIGHT_ANNOTATION_KEY, "")
    if not hierarchy and not weights:
        return
    paths = hierarchy.split("/")
    wparts = weights.split("/")
    if len(paths) != len(wparts):
        deny(f"{KUBE_HIERARCHY_ANNOTATION_KEY} must have the same length "
             f"with {KUBE_HIERARCHY_WEIGHT_ANNOTATION_KEY}")
    for w in wparts:
        # Go's strconv.ParseFloat rejects underscores and surrounding
        # whitespace that Python's float() tolerates
        if w != w.strip() or "_" in w:
            deny(f"{w} in the {weights} is invalid number")
        try:
            wf = float(w)
        except ValueError:
            deny(f"{w} in the {weights} is invalid number")
        else:
            if wf <= 0:
                deny(f"{w} in the {weights} must be larger than 0")
    for other in store.list("Queue"):
        other_h = other.metadata.annotations.get(
            KUBE_HIERARCHY_ANNOTATION_KEY, "")
        if (other_h and other.metadata.name != queue.metadata.name
                and other_h.startswith(hierarchy)):
            deny(f"{hierarchy} is not allowed to be in the sub path of "
                 f"{other_h} of queue {other.metadata.name}")


def mutate_podgroup(operation: str, pg: PodGroupCR, old) -> PodGroupCR:
    if not pg.spec.queue:
        pg.spec.queue = "default"
    return pg


def validate_podgroup(operation: str, pg: PodGroupCR, old) -> None:
    """Reject malformed elastic-gang specs at the door
    (docs/design/elastic-gangs.md): a desired below min would make the
    min/desired decision class degenerate (the scheduler clamps, but the
    clamp is a crash-consistency net, not an API), and the suspend mark
    only takes "true"/"false" so the Command funnel's rewrites stay
    round-trippable."""
    from ..elastic_gang.membership import (ELASTIC_DESIRED_ANNOTATION,
                                           SUSPEND_ANNOTATION)
    ann = pg.metadata.annotations or {}
    if ELASTIC_DESIRED_ANNOTATION in ann:
        raw = ann[ELASTIC_DESIRED_ANNOTATION]
        try:
            desired = int(str(raw).strip())
        except (TypeError, ValueError):
            deny(f"invalid value <{raw}> for {ELASTIC_DESIRED_ANNOTATION}, "
                 f"it must be an integer")
        if desired < max(pg.spec.min_member, 1):
            deny(f"invalid value <{desired}> for "
                 f"{ELASTIC_DESIRED_ANNOTATION}: desired members must be "
                 f">= minMember ({pg.spec.min_member})")
    sus = ann.get(SUSPEND_ANNOTATION)
    if sus is not None and sus not in ("true", "false"):
        deny(f"invalid value <{sus}> for {SUSPEND_ANNOTATION}, "
             f"it must be \"true\" or \"false\"")


# pods webhook (admit_pod.go:1-203) ------------------------------------------

JDB_MIN_AVAILABLE = "volcano.sh/jdb-min-available"
JDB_MAX_UNAVAILABLE = "volcano.sh/jdb-max-unavailable"


def _validate_int_percentage(key: str, value: str) -> None:
    """admit_pod.go validateIntPercentageStr: positive int, or 1%-99%."""
    s = str(value).strip()
    if s.endswith("%"):
        try:
            v = int(s[:-1])
        except ValueError:
            deny(f"invalid value {s} for {key}")
        if v <= 0 or v >= 100:
            deny(f"invalid value <{s}> for {key}, it must be a valid "
                 f"percentage which between 1% ~ 99%")
        return
    try:
        v = int(s)
    except ValueError:
        deny(f"invalid type: neither int nor percentage for {key}")
    if v <= 0:
        deny(f"invalid value <{s}> for {key}, it must be a positive integer")


def make_validate_pod(store: ObjectStore, scheduler_name: str = "volcano"):
    """Gate bare-pod creation on its PodGroup phase (admit_pod.go
    validatePod): allow when the pod isn't ours, when the group is already
    schedulable, or when a normal pod has no group yet; deny while the
    group is Pending. Also validates disruption-budget annotations."""
    from ..api import PodGroupPhase
    from ..cache.store_wiring import GROUP_NAME_ANNOTATION

    def check_pg_phase(pod, pg_name: str, is_vc_job: bool) -> None:
        pg: PodGroupCR = store.get("PodGroup", pod.metadata.namespace,
                                   pg_name)
        if pg is None:
            if is_vc_job:
                deny(f"failed to get PodGroup for pod "
                     f"<{pod.metadata.namespace}/{pod.metadata.name}>")
            return
        if pg.status.phase == PodGroupPhase.PENDING:
            deny(f"failed to create pod <{pod.metadata.namespace}/"
                 f"{pod.metadata.name}> as the podgroup phase is Pending")

    def validate_pod(operation: str, pod, old) -> None:
        if pod.scheduler_name != scheduler_name:
            return
        pg_name = pod.metadata.annotations.get(GROUP_NAME_ANNOTATION, "")
        if pg_name:
            check_pg_phase(pod, pg_name, is_vc_job=True)
        else:
            # normal pod: the name the podgroup controller would generate
            check_pg_phase(pod, f"podgroup-{pod.metadata.uid}",
                           is_vc_job=False)
        budget_keys = [k for k in (JDB_MIN_AVAILABLE, JDB_MAX_UNAVAILABLE)
                       if k in pod.metadata.annotations]
        for key in budget_keys:
            _validate_int_percentage(key, pod.metadata.annotations[key])
        if len(budget_keys) > 1:
            deny(f"not allow configure multiple annotations "
                 f"<{[JDB_MIN_AVAILABLE, JDB_MAX_UNAVAILABLE]}> at same time")

    return validate_pod


def register_webhooks(store: ObjectStore) -> Router:
    """Self-registration analogue (cmd/webhook-manager/app/server.go:41-108):
    build the router, bind every admission service, attach to the store."""
    router = Router()
    router.register(AdmissionService(
        "/jobs/mutate", ["Job"], ["CREATE"], mutate_job, mutating=True))
    router.register(AdmissionService(
        "/jobs/validate", ["Job"], ["CREATE", "UPDATE"],
        make_validate_job(store)))
    router.register(AdmissionService(
        "/queues/mutate", ["Queue"], ["CREATE"], mutate_queue, mutating=True))
    router.register(AdmissionService(
        "/queues/validate", ["Queue"], ["CREATE", "UPDATE", "DELETE"],
        make_validate_queue(store)))
    router.register(AdmissionService(
        "/podgroups/mutate", ["PodGroup"], ["CREATE"], mutate_podgroup,
        mutating=True))
    router.register(AdmissionService(
        "/podgroups/validate", ["PodGroup"], ["CREATE", "UPDATE"],
        validate_podgroup))
    router.register(AdmissionService(
        "/pods", ["Pod"], ["CREATE"], make_validate_pod(store)))
    store.register_admission_hook(router.hook)
    return router
