"""Admission service registry + dispatch.

Mirrors /root/reference/pkg/webhooks/router/{admission.go:30-48,server.go} —
an AdmissionService binds a path to a mutate/validate func for a set of
kinds+operations; the Router plays the HTTPS server role, dispatching store
admission callbacks to the registered services in path order.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..store import AdmissionError


class AdmissionService:
    def __init__(self, path: str, kinds: List[str], operations: List[str],
                 func: Callable, mutating: bool = False):
        self.path = path
        self.kinds = set(kinds)
        self.operations = set(operations)
        self.func = func
        self.mutating = mutating


class Router:
    def __init__(self):
        self.services: List[AdmissionService] = []

    def register(self, service: AdmissionService) -> None:
        self.services.append(service)
        self.services.sort(key=lambda s: (not s.mutating, s.path))

    def hook(self, operation: str, kind: str, obj, old):
        """ObjectStore admission hook: mutating services run first (matching
        the reference's webhook ordering), then validators; a validator
        raising AdmissionError denies the request."""
        for service in self.services:
            if kind not in service.kinds or operation not in service.operations:
                continue
            result = service.func(operation, obj, old)
            if service.mutating and result is not None:
                obj = result
        return obj


def deny(message: str) -> None:
    raise AdmissionError(message)
