"""Admission webhooks (mirrors /root/reference/pkg/webhooks): mutating
defaults + validating rules, registered as ObjectStore admission hooks (the
in-process analogue of the TLS webhook server + AdmissionReview plumbing in
pkg/webhooks/router)."""

from .admission import register_webhooks
from .router import AdmissionService, Router

__all__ = ["AdmissionService", "Router", "register_webhooks"]
