"""Full-system assembly: a Volcano-equivalent cluster in one process.

Wires together the three control-plane components + CLI surface
(SURVEY.md §1 layer map): ObjectStore (API server/etcd), webhook router
(vc-webhook-manager), controllers (vc-controller-manager), and the
Scheduler over a store-wired cache (vc-scheduler).
"""

from __future__ import annotations

from typing import Optional

from .apis.objects import ObjectMeta, QueueCR, QueueSpecCR
from .cache.store_wiring import wire_cache_to_store
from .cli.vcctl import JobCommands, QueueCommands
from .controllers import start_controllers
from .scheduler import Scheduler
from .store import ObjectStore
from .webhooks import register_webhooks


class VolcanoSystem:
    def __init__(self, conf_text: Optional[str] = None,
                 schedule_period: float = 1.0,
                 default_queue: str = "default",
                 store: Optional[ObjectStore] = None,
                 native_store: bool = False):
        """native_store=True backs the API-server state with the C++ store
        (volcano_tpu.native), falling back to the Python ObjectStore when
        no toolchain is available."""
        if store is not None:
            self.store = store
        elif native_store:
            from .native import make_object_store
            self.store = make_object_store(prefer_native=True)
        else:
            self.store = ObjectStore()
        self.router = register_webhooks(self.store)
        self.controllers = start_controllers(self.store)
        if default_queue:
            self.store.create(QueueCR(
                metadata=ObjectMeta(name=default_queue, namespace="default"),
                spec=QueueSpecCR(weight=1)))
        # the scheduler's connection to the store rides the retrying
        # transport funnel (docs/robustness.md store failure model):
        # every scheduler-side verb gets bounded retry with backoff +
        # jitter under a per-cycle budget, degrading to resync past it.
        # Controllers/webhooks/CLI keep the raw store — they are other
        # components with their own (store-side) semantics.
        from .store_transport import RetryingStoreTransport
        self.scheduler_transport = RetryingStoreTransport(self.store)
        self.cache = wire_cache_to_store(self.scheduler_transport)
        self.scheduler = Scheduler(self.cache, conf_text=conf_text,
                                   schedule_period=schedule_period)
        self.jobs = JobCommands(self.store)
        self.queues = QueueCommands(self.store)

    def schedule_once(self):
        """One drained scheduling cycle. Returns the cycle's isolated
        per-action failures ([] when clean) — a misconfigured action (say
        an unknown allocate engine) no longer raises out of run_once, so
        programmatic callers must check the returned list (the shell's
        run() loop does the equivalent via its crash-loop guard)."""
        self._drain_controllers()
        errors = self.scheduler.run_once()
        self._drain_controllers()
        return errors

    def _drain_controllers(self) -> None:
        """Coalesced controller work (the workqueue worker analogue): jobs
        whose pods churned get one sync, not one per pod event."""
        for c in self.controllers:
            if hasattr(c, "process_dirty"):
                c.process_dirty()

    def start(self):
        return self.scheduler.start()

    def stop(self) -> None:
        self.scheduler.stop()
