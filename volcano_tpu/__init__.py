"""volcano_tpu — a TPU-native batch scheduling framework with the capability
surface of Volcano (gang scheduling, multi-queue fairness, preempt/reclaim,
binpack placement, job lifecycle, admission, CLI), whose per-cycle placement
math runs as batched array programs on TPU via JAX/XLA.

Layer map (mirrors SURVEY.md §1, re-architected TPU-first):

- ``api``         in-memory scheduling model (dense-tensor friendly)
- ``ops``         pure-JAX kernels: fit masks, scores, placement, fairness
- ``framework``   Session / Statement / tiers / conf — the semantics layer
- ``plugins``     gang, drf, proportion, binpack, predicates, ... as array transforms
- ``actions``     enqueue, allocate / allocate-tpu, backfill
- ``cache``       cluster-state cache, snapshot marshaling, side-effect executors
- ``metrics``     Prometheus metrics with the reference's metric names
- ``utils``       priority queue, scheduler helpers
"""

__version__ = "0.1.0"
