"""The Session: one snapshot-scoped scheduling transaction.

Mirrors /root/reference/pkg/scheduler/framework/session.go:38-437 and the
tiered dispatch semantics of session_plugins.go:130-725 (intersection+veto
for victim selection, first-nonzero for order fns, vote semantics for
pipelined/enqueueable, sum for node order).

TPU-first extension: besides the reference's per-object callbacks, plugins
can register *tensor contributions* — static feasibility masks ``bool[T,N]``,
static score matrices ``f32[T,N]``, and weights for the in-kernel dynamic
scorers — which the allocate action assembles into one device solve
(see volcano_tpu.cache.snapshot and volcano_tpu.actions.allocate).
"""

from __future__ import annotations

import itertools
import uuid
from typing import Callable, Dict, List, Optional

from ..api import (ClusterInfo, JobInfo, NodeInfo, QueueInfo, TaskInfo,
                   TaskStatus)
from ..obs.audit import AUDIT
from .conf import Configuration, Tier

# Vote values (plugins/util/util.go Permit/Abstain/Reject).
PERMIT = 1
ABSTAIN = 0
REJECT = -1


class ValidateResult:
    def __init__(self, passed: bool, reason: str = "", message: str = ""):
        self.passed = passed
        self.reason = reason
        self.message = message


class Event:
    def __init__(self, task: TaskInfo, err: Optional[Exception] = None):
        self.task = task
        self.err = err


class EventHandler:
    def __init__(self, allocate_func: Optional[Callable[[Event], None]] = None,
                 deallocate_func: Optional[Callable[[Event], None]] = None,
                 aggregatable: bool = False):
        """aggregatable=True declares the handler's effect is additive in
        ``event.task.resreq`` within a job (drf/proportion share updates):
        batched engines may then fire one aggregated event per job instead
        of one per task."""
        self.allocate_func = allocate_func
        self.deallocate_func = deallocate_func
        self.aggregatable = aggregatable


class Session:
    def __init__(self, cache, tiers: List[Tier],
                 configurations: List[Configuration],
                 time_fn: Optional[Callable[[], float]] = None,
                 snapshot: Optional[ClusterInfo] = None):
        self.uid = str(uuid.uuid4())
        self.cache = cache
        self.tiers = tiers
        self.configurations = configurations
        # speculative sessions (docs/performance.md pipelining) are
        # opened on a read-only staged snapshot
        # (cache.speculative_snapshot); open_session flips these. A
        # speculative session either PROMOTES (the pipelined shell's
        # conflict check passed — speculative cleared, the staged
        # snapshot adopted, the session becomes the cycle's real one) or
        # is abandoned without close-time writebacks.
        self.speculative = False
        self.spec_basis = None          # staged-snapshot bookkeeping
        self._pinned_epoch = None       # TensorEpochView held for a solve
        # Injectable session clock (vlint VT002, docs/simulation.md):
        # plugin decision callbacks (sla deadlines, tdm zone windows, gang
        # condition timestamps) read "now" through ssn.now() instead of
        # the wall clock, so the scheduler shell can pin it to its clock
        # (WallClock.now in production, the sim's VirtualClock under
        # replay) and decisions stay byte-deterministic. The default is a
        # wall-time reference for sessions opened outside a shell
        # (tests, bench one-offs).
        import time as _time
        self._time_fn: Callable[[], float] = time_fn or _time.time

        if snapshot is None:
            snapshot = cache.snapshot()
        self.jobs: Dict[str, JobInfo] = snapshot.jobs
        self.nodes: Dict[str, NodeInfo] = snapshot.nodes
        # which snapshot generation this session was opened on — the
        # persistent-tensor refresh refuses to apply a stale session's
        # delta over a newer snapshot's (cache.tensor_refresh)
        self.snap_epoch = getattr(snapshot, "snap_epoch", None)
        self.queues: Dict[str, QueueInfo] = snapshot.queues
        self.namespaces = snapshot.namespaces
        self.revocable_nodes = snapshot.revocable_nodes
        self.node_list: List[NodeInfo] = list(snapshot.nodes.values())
        self.total_resource = None  # set by plugins that need it

        self.plugins: Dict[str, object] = {}
        self.event_handlers: List[EventHandler] = []

        # callback registries (session.go:58-80)
        self.job_order_fns: Dict[str, Callable] = {}
        self.queue_order_fns: Dict[str, Callable] = {}
        self.task_order_fns: Dict[str, Callable] = {}
        self.namespace_order_fns: Dict[str, Callable] = {}
        self.cluster_order_fns: Dict[str, Callable] = {}
        self.predicate_fns: Dict[str, Callable] = {}
        self.best_node_fns: Dict[str, Callable] = {}
        self.node_order_fns: Dict[str, Callable] = {}
        self.batch_node_order_fns: Dict[str, Callable] = {}
        self.node_map_fns: Dict[str, Callable] = {}
        self.node_reduce_fns: Dict[str, Callable] = {}
        self.preemptable_fns: Dict[str, Callable] = {}
        self.reclaimable_fns: Dict[str, Callable] = {}
        self.overused_fns: Dict[str, Callable] = {}
        self.job_ready_fns: Dict[str, Callable] = {}
        self.job_pipelined_fns: Dict[str, Callable] = {}
        self.job_valid_fns: Dict[str, Callable] = {}
        self.job_enqueueable_fns: Dict[str, Callable] = {}
        self.job_enqueued_fns: Dict[str, Callable] = {}
        self.target_job_fns: Dict[str, Callable] = {}
        self.reserved_nodes_fns: Dict[str, Callable] = {}
        self.victim_tasks_fns: Dict[str, Callable] = {}
        self.job_starving_fns: Dict[str, Callable] = {}

        # TPU tensor-contribution registries: fns of (session, snapshot
        # tensors, tasks) -> arrays, assembled by SnapshotTensors.
        self.feasibility_fns: Dict[str, Callable] = {}
        self.static_score_fns: Dict[str, Callable] = {}
        self.dynamic_score_weights: Dict[str, dict] = {}
        # plugins whose predicate depends on state mutated during the cycle
        # (gpu card packing, numa cpusets): batched engines must re-validate
        # device proposals through predicate_fn at replay time
        self.stateful_predicates: set = set()
        # proportion publishes its per-queue deserved vectors here so the
        # device reclaim engine can replay its tier in-kernel
        self.queue_deserved: Dict[str, "Resource"] = {}
        # decision-audit feed (obs.audit): (kind, task_uid, job_uid, extra)
        # tuples appended by dispatch/evict/statement commits, harvested by
        # the scheduler shell after close_session
        self.audit_events: list = []

    def now(self) -> float:
        """The session's time source — wall seconds in production,
        virtual seconds under sim replay. Decision callbacks MUST read
        time through this (vlint VT002) so replays are deterministic;
        the timebase matches job creation_timestamps (wall via the api
        defaults live, virtual via the trace in the sim)."""
        return self._time_fn()

    # -- registration helpers (AddXxxFn of session_plugins.go) --------------

    def add_job_order_fn(self, name, fn): self.job_order_fns[name] = fn
    def add_queue_order_fn(self, name, fn): self.queue_order_fns[name] = fn
    def add_task_order_fn(self, name, fn): self.task_order_fns[name] = fn
    def add_namespace_order_fn(self, name, fn): self.namespace_order_fns[name] = fn
    def add_predicate_fn(self, name, fn): self.predicate_fns[name] = fn
    def add_best_node_fn(self, name, fn): self.best_node_fns[name] = fn
    def add_node_order_fn(self, name, fn): self.node_order_fns[name] = fn
    def add_batch_node_order_fn(self, name, fn): self.batch_node_order_fns[name] = fn
    def add_node_map_fn(self, name, fn): self.node_map_fns[name] = fn
    def add_node_reduce_fn(self, name, fn): self.node_reduce_fns[name] = fn
    def add_preemptable_fn(self, name, fn): self.preemptable_fns[name] = fn
    def add_reclaimable_fn(self, name, fn): self.reclaimable_fns[name] = fn
    def add_overused_fn(self, name, fn): self.overused_fns[name] = fn
    def add_job_ready_fn(self, name, fn): self.job_ready_fns[name] = fn
    def add_job_pipelined_fn(self, name, fn): self.job_pipelined_fns[name] = fn
    def add_job_valid_fn(self, name, fn): self.job_valid_fns[name] = fn
    def add_job_enqueueable_fn(self, name, fn): self.job_enqueueable_fns[name] = fn
    def add_job_enqueued_fn(self, name, fn): self.job_enqueued_fns[name] = fn
    def add_target_job_fn(self, name, fn): self.target_job_fns[name] = fn
    def add_reserved_nodes_fn(self, name, fn): self.reserved_nodes_fns[name] = fn
    def add_victim_tasks_fn(self, name, fn): self.victim_tasks_fns[name] = fn
    def add_job_starving_fn(self, name, fn): self.job_starving_fns[name] = fn
    def add_event_handler(self, eh: EventHandler): self.event_handlers.append(eh)

    def add_feasibility_fn(self, name, fn): self.feasibility_fns[name] = fn
    def add_static_score_fn(self, name, fn): self.static_score_fns[name] = fn

    def set_dynamic_score_weights(self, name, **weights):
        self.dynamic_score_weights[name] = weights

    # -- tier iteration helper ----------------------------------------------

    def _enabled_fns(self, registry: Dict[str, Callable], flag: Optional[str]):
        """Yield (tier_index, fn) for each enabled registered plugin, in tier
        order."""
        for ti, tier in enumerate(self.tiers):
            for opt in tier.plugins:
                if flag is not None and not opt.is_enabled(flag):
                    continue
                fn = registry.get(opt.name)
                if fn is not None:
                    yield ti, fn

    # -- order fns: first non-zero comparison wins --------------------------

    def _order(self, registry, flag, l, r, fallback) -> bool:
        for _, fn in self._enabled_fns(registry, flag):
            j = fn(l, r)
            if j != 0:
                return j < 0
        return fallback(l, r)

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        return self._order(self.job_order_fns, "enabledJobOrder", l, r,
                           lambda a, b: (a.creation_timestamp, a.uid)
                           < (b.creation_timestamp, b.uid))

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        return self._order(self.queue_order_fns, "enabledQueueOrder", l, r,
                           lambda a, b: a.creation_timestamp < b.creation_timestamp
                           if hasattr(a, "creation_timestamp") else a.uid < b.uid)

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        return self._order(self.task_order_fns, "enabledTaskOrder", l, r,
                           lambda a, b: (a.creation_timestamp, a.uid)
                           < (b.creation_timestamp, b.uid))

    def namespace_order_fn(self, l, r) -> bool:
        return self._order(self.namespace_order_fns, "enabledNamespaceOrder",
                           l, r, lambda a, b: str(a) < str(b))

    # -- predicates / scoring ----------------------------------------------

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """All enabled predicates must pass; raises FitError-carrying
        ValueError on failure (session_plugins.go PredicateFn)."""
        for _, fn in self._enabled_fns(self.predicate_fns, "enabledPredicate"):
            fn(task, node)

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        score = 0.0
        for _, fn in self._enabled_fns(self.node_order_fns, "enabledNodeOrder"):
            score += fn(task, node)
        return score

    def batch_node_order_fn(self, task: TaskInfo, nodes: List[NodeInfo]) -> Dict[str, float]:
        scores: Dict[str, float] = {n.name: 0.0 for n in nodes}
        for _, fn in self._enabled_fns(self.batch_node_order_fns, "enabledNodeOrder"):
            for name, s in fn(task, nodes).items():
                scores[name] = scores.get(name, 0.0) + s
        return scores

    def best_node_fn(self, task: TaskInfo, node_scores) -> Optional[NodeInfo]:
        for _, fn in self._enabled_fns(self.best_node_fns, "enabledBestNode"):
            best = fn(task, node_scores)
            if best is not None:
                return best
        return None

    # -- victim selection: per-tier intersection with veto ------------------

    def _tiered_victims(self, registry, flag, invoke) -> List[TaskInfo]:
        for ti, tier in enumerate(self.tiers):
            victims: Optional[List[TaskInfo]] = None
            init = False
            for opt in tier.plugins:
                if flag is not None and not opt.is_enabled(flag):
                    continue
                fn = registry.get(opt.name)
                if fn is None:
                    continue
                result = invoke(fn)
                if result is None:      # abstain
                    continue
                candidates = result
                if not candidates:      # veto: this tier yields nothing
                    victims = None
                    break
                if not init:
                    victims = list(candidates)
                    init = True
                else:
                    cand_ids = {c.uid for c in candidates}
                    victims = [v for v in victims if v.uid in cand_ids]
            if victims is not None:
                return victims
        return []

    def preemptable(self, preemptor: TaskInfo,
                    preemptees: List[TaskInfo]) -> List[TaskInfo]:
        """session_plugins.go:187-236. Plugin fns return (candidates, vote);
        vote ABSTAIN means the plugin abstains."""
        def invoke(fn):
            candidates, vote = fn(preemptor, preemptees)
            return None if vote == ABSTAIN else candidates
        return self._tiered_victims(self.preemptable_fns, "enabledPreemptable",
                                    invoke)

    def reclaimable(self, reclaimer: TaskInfo,
                    reclaimees: List[TaskInfo]) -> List[TaskInfo]:
        def invoke(fn):
            candidates, vote = fn(reclaimer, reclaimees)
            return None if vote == ABSTAIN else candidates
        return self._tiered_victims(self.reclaimable_fns, "enabledReclaimable",
                                    invoke)

    def victim_tasks(self) -> List[TaskInfo]:
        return self._tiered_victims(self.victim_tasks_fns, "enabledVictim",
                                    lambda fn: fn())

    # -- job votes ----------------------------------------------------------

    def overused(self, queue: QueueInfo) -> bool:
        for _, fn in self._enabled_fns(self.overused_fns, None):
            if fn(queue):
                return True
        return False

    def job_ready(self, job: JobInfo) -> bool:
        """All registered fns in the first tier that has any must agree
        (session_plugins.go JobReady)."""
        for ti, tier in enumerate(self.tiers):
            found = False
            for opt in tier.plugins:
                if not opt.is_enabled("enabledJobReady"):
                    continue
                fn = self.job_ready_fns.get(opt.name)
                if fn is None:
                    continue
                found = True
                if not fn(job):
                    return False
            if found:
                return True
        return True

    def _vote(self, registry, flag, obj) -> bool:
        """Permit/abstain/reject tier voting (JobPipelined/JobEnqueueable)."""
        for tier in self.tiers:
            has_permit = False
            for opt in tier.plugins:
                if not opt.is_enabled(flag):
                    continue
                fn = registry.get(opt.name)
                if fn is None:
                    continue
                res = fn(obj)
                if res < 0:
                    return False
                if res > 0:
                    has_permit = True
            if has_permit:
                return True
        return True

    def job_pipelined(self, job: JobInfo) -> bool:
        return self._vote(self.job_pipelined_fns, "enabledJobPipelined", job)

    def job_enqueueable(self, job: JobInfo) -> bool:
        return self._vote(self.job_enqueueable_fns, "enabledJobEnqueued", job)

    def job_enqueued(self, job: JobInfo) -> None:
        for _, fn in self._enabled_fns(self.job_enqueued_fns, "enabledJobEnqueued"):
            fn(job)

    def job_starving(self, job: JobInfo) -> bool:
        found = False
        for ti, tier in enumerate(self.tiers):
            for opt in tier.plugins:
                if not opt.is_enabled("enabledJobStarving"):
                    continue
                fn = self.job_starving_fns.get(opt.name)
                if fn is None:
                    continue
                found = True
                if not fn(job):
                    return False
            if found:
                return True
        return False

    def job_valid(self, job: JobInfo) -> Optional[ValidateResult]:
        for _, fn in self._enabled_fns(self.job_valid_fns, None):
            vr = fn(job)
            if vr is not None and not vr.passed:
                return vr
        return None

    def target_job(self, jobs: List[JobInfo]) -> Optional[JobInfo]:
        for _, fn in self._enabled_fns(self.target_job_fns, "enabledTargetJob"):
            return fn(jobs)
        return None

    def reserved_nodes(self) -> None:
        for _, fn in self._enabled_fns(self.reserved_nodes_fns,
                                       "enabledReservedNodes"):
            fn()

    # -- state mutation (session.go:224-397) --------------------------------

    def _fire_allocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.allocate_func:
                eh.allocate_func(Event(task))

    def _fire_deallocate(self, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            if eh.deallocate_func:
                eh.deallocate_func(Event(task))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.jobs[task.job]
        job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        self.nodes[hostname].add_task(task)
        self._fire_allocate(task)

    def allocate(self, task: TaskInfo, node: NodeInfo) -> None:
        """Direct allocation (used by backfill): statusify, occupy node,
        fire events, and dispatch the bind immediately if the gang is ready
        (session.go:267-358)."""
        job = self.jobs[task.job]
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = node.name
        self.nodes[node.name].add_task(task)
        self._fire_allocate(task)
        if self.job_ready(job):
            self.dispatch(task)

    def _audit_event(self, kind: str, task: TaskInfo,
                     extra: str = "") -> None:
        """Feed the decision audit (obs.audit) — a no-op unless the audit
        ring is enabled."""
        if AUDIT.enabled:
            self.audit_events.append((kind, task.uid, task.job, extra))

    def dispatch(self, task: TaskInfo) -> None:
        self.jobs[task.job].update_task_status(task, TaskStatus.BINDING)
        self._audit_event("bind", task, task.node_name)
        self.cache.bind(task)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Direct eviction (used by reclaim): session state + cache side
        effect (session.go:360-397)."""
        job = self.jobs[reclaimee.job]
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.nodes[reclaimee.node_name]
        node.update_task(job.tasks[reclaimee.uid])
        self._fire_deallocate(reclaimee)
        self._audit_event("evict", reclaimee, reason)
        self.cache.evict(reclaimee, reason)

    def bind_pod_group(self, job: JobInfo, cluster: str) -> None:
        """Forward a gang to a silo cluster (session.go:399-402 ->
        cache.BindPodGroup, the multi-cluster path)."""
        self.cache.bind_pod_group(job, cluster)

    def update_scheduler_numa_info(self, numa_sets) -> None:
        """session.go:435-437 — forward cpuset assignments to the cache."""
        update = getattr(self.cache, "update_scheduler_numa_info", None)
        if update is not None:
            update(numa_sets)

    def update_pod_group_condition(self, job: JobInfo, condition: dict) -> None:
        """Replace the same-type condition (bounded: one entry per type, like
        PodGroup status conditions on the CR); mark dirty only on a real
        transition so the close-time writeback can dedup."""
        conditions = job.podgroup.conditions
        for i, existing in enumerate(conditions):
            if existing.get("type") == condition.get("type"):
                changed = any(existing.get(k) != condition.get(k)
                              for k in ("status", "reason", "message"))
                conditions[i] = condition
                if changed:
                    job.podgroup.conditions_dirty = True
                return
        conditions.append(condition)
        job.podgroup.conditions_dirty = True

    def statement(self) -> "Statement":
        from .statement import Statement
        return Statement(self)

    # -- persistent tensor state (docs/performance.md) ----------------------

    def snapshot_node_tensors(self, rnames):
        """Device-resident NodeTensors for this session's snapshot, kept
        alive across cycles by the cache and scatter-updated from the dirty
        set. Only valid while NO session mutation has touched node state —
        the ``_touched`` witness every NodeInfo mutation sets — because the
        persistent rows mirror snapshot-time values; after the first
        statement replays, mid-cycle consumers (stateful re-solve rounds,
        preempt/reclaim) must marshal from the live session objects
        instead. Returns None whenever the incremental path cannot prove
        itself exact; callers fall back to a from-scratch NodeTensors.

        SPECULATIVE sessions route through the cache's staged refresh
        (``tensor_refresh_speculative``): the scatter is value-idempotent
        and nothing is consumed, and the returned ``TensorEpochView`` is
        the PINNED epoch the in-flight solve reads while later binds
        publish the other half of the pair — held on the session for the
        shell to retire at commit/discard."""
        if self.speculative:
            refresh = getattr(self.cache, "tensor_refresh_speculative",
                              None)
            if refresh is None or self.spec_basis is None:
                return None
        else:
            refresh = getattr(self.cache, "tensor_refresh", None)
            if refresh is None:
                return None
        for node in self.nodes.values():
            if getattr(node, "_touched", True):
                return None
        try:
            if self.speculative:
                view = refresh(self.nodes, rnames, self.spec_basis)
                if view is not None:
                    self._pinned_epoch = view
                return view
            return refresh(self.nodes, rnames, self.snap_epoch)
        except Exception as exc:
            import logging
            # the scatter update runs eager device ops, so a real XLA
            # OOM/device-lost can surface HERE, not just inside the
            # allocate solve — classify it and feed the same cool-down
            # state machine instead of silently retrying every cycle
            # (docs/robustness.md device-fault containment)
            from ..device_health import DEVICE_HEALTH, classify_device_fault
            kind = classify_device_fault(exc)
            if kind is not None:
                DEVICE_HEALTH.record_fault(kind)
                invalidate = getattr(self.cache, "invalidate_device_state",
                                     None)
                if invalidate is not None:
                    invalidate()
                logging.getLogger(__name__).error(
                    "device fault (%s) during persistent tensor refresh; "
                    "cooling down, rebuilding from host truth", kind)
            else:
                logging.getLogger(__name__).exception(
                    "persistent tensor refresh failed; rebuilding from "
                    "scratch")
            return None
