"""Statement: the all-or-nothing gang transaction.

Mirrors /root/reference/pkg/scheduler/framework/statement.go:46-395 — an
undo log of Allocate/Pipeline/Evict operations against session state;
``commit()`` flushes side effects to the cache (binds/evictions), ``discard()``
rolls everything back in reverse order. This is the correctness contract the
TPU solver's proposals are applied through: device output is only a proposal
until a Statement commits it.
"""

from __future__ import annotations

from typing import List, NamedTuple

from ..api import TaskInfo, TaskStatus

ALLOCATE = "allocate"
PIPELINE = "pipeline"
EVICT = "evict"


class _Op(NamedTuple):
    name: str
    task: TaskInfo
    reason: str = ""


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[_Op] = []

    # -- speculative ops (recorded; session state mutated now) --------------

    def allocate(self, task: TaskInfo, node) -> None:
        """statement.go:229-289."""
        hostname = node.name if hasattr(node, "name") else node
        job = self.ssn.jobs[task.job]
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        self.ssn.nodes[hostname].add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(_Op(ALLOCATE, task))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """statement.go:145-185."""
        job = self.ssn.jobs[task.job]
        job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        self.ssn.nodes[hostname].add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(_Op(PIPELINE, task))

    def evict(self, reclaimee: TaskInfo, reason: str = "") -> None:
        """statement.go:59-96."""
        job = self.ssn.jobs[reclaimee.job]
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(job.tasks[reclaimee.uid])
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append(_Op(EVICT, reclaimee, reason))

    # -- undo ops (statement.go:110-143,190-227,318-350) --------------------

    def _unallocate(self, task: TaskInfo) -> None:
        job = self.ssn.jobs[task.job]
        job.update_task_status(task, TaskStatus.PENDING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs[task.job]
        job.update_task_status(task, TaskStatus.PENDING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    def _unevict(self, task: TaskInfo) -> None:
        job = self.ssn.jobs[task.job]
        job.update_task_status(task, TaskStatus.RUNNING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.update_task(job.tasks[task.uid])
        self.ssn._fire_allocate(task)

    # -- terminal -----------------------------------------------------------

    def discard(self) -> None:
        """Roll back all recorded operations in reverse (statement.go:352-374)."""
        for op in reversed(self.operations):
            if op.name == ALLOCATE:
                self._unallocate(op.task)
            elif op.name == PIPELINE:
                self._unpipeline(op.task)
            elif op.name == EVICT:
                self._unevict(op.task)
        self.operations.clear()

    def commit(self) -> None:
        """Flush side effects: binds for allocations, evictions to the cache;
        pipelines stay session-only (statement.go:377-395)."""
        for op in self.operations:
            if op.name == ALLOCATE:
                self.ssn.dispatch(op.task)
            elif op.name == EVICT:
                self.ssn._audit_event("evict", op.task, op.reason)
                self.ssn.cache.evict(op.task, op.reason)
        self.operations.clear()
