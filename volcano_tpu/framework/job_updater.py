"""Session-close writeback of PodGroup status, mirroring
/root/reference/pkg/scheduler/framework/job_updater.go:85-108 (the reference
fans out over 16 workers; here the cache write is in-process so a loop
suffices — dedup on unchanged status is kept).
"""

from __future__ import annotations

from ..api import PodGroupPhase, TaskStatus, allocated_status


def job_terminated(job) -> bool:
    return all(t.status in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)
               for t in job.tasks.values()) and bool(job.tasks)


def _phase_for(job) -> PodGroupPhase:
    if job.podgroup.phase == PodGroupPhase.PENDING:
        return PodGroupPhase.PENDING
    running = sum(1 for t in job.tasks.values()
                  if t.status == TaskStatus.RUNNING or allocated_status(t.status))
    if running >= job.min_available and job.min_available > 0:
        return PodGroupPhase.RUNNING
    return job.podgroup.phase


def update_all(ssn) -> None:
    for job in ssn.jobs.values():
        pg = job.podgroup
        running = sum(1 for t in job.tasks.values()
                      if t.status == TaskStatus.RUNNING)
        succeeded = sum(1 for t in job.tasks.values()
                        if t.status == TaskStatus.SUCCEEDED)
        failed = sum(1 for t in job.tasks.values()
                     if t.status == TaskStatus.FAILED)
        new_phase = _phase_for(job)
        changed = (pg.running != running or pg.succeeded != succeeded
                   or pg.failed != failed or pg.phase != new_phase
                   or pg.conditions_dirty)
        if not changed:
            continue
        pg.running, pg.succeeded, pg.failed = running, succeeded, failed
        pg.phase = new_phase
        pg.conditions_dirty = False
        ssn.cache.update_job_status(job)
