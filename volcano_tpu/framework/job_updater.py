"""Session-close writeback of PodGroup status, mirroring
/root/reference/pkg/scheduler/framework/job_updater.go:85-108 (the reference
fans out over 16 workers; here the cache write is in-process so a loop
suffices — dedup on unchanged status is kept).
"""

from __future__ import annotations

from ..api import PodGroupPhase, TaskStatus, allocated_status


def job_terminated(job) -> bool:
    return all(t.status in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)
               for t in job.tasks.values()) and bool(job.tasks)


def _phase_for(job, ssn_uid: str) -> PodGroupPhase:
    """jobStatus (session.go:176-214): running tasks + an Unschedulable
    condition from THIS session -> Unknown (the split-gang signal the job
    controller turns into a JobUnknown event); else enough allocated (or
    succeeded) members -> Running; else non-Inqueue groups fall back to
    Pending."""
    if job.podgroup.phase == PodGroupPhase.PENDING:
        return PodGroupPhase.PENDING
    unschedulable = any(
        c.get("type") == "Unschedulable" and c.get("status") == "True"
        and c.get("transitionID") == ssn_uid
        for c in job.podgroup.conditions)
    running = sum(1 for t in job.tasks.values()
                  if t.status == TaskStatus.RUNNING)
    if running and unschedulable:
        return PodGroupPhase.UNKNOWN
    allocated = sum(1 for t in job.tasks.values()
                    if allocated_status(t.status)
                    or t.status == TaskStatus.SUCCEEDED)
    if allocated >= job.min_available and job.min_available > 0:
        return PodGroupPhase.RUNNING
    if job.podgroup.phase != PodGroupPhase.INQUEUE:
        return PodGroupPhase.PENDING
    return job.podgroup.phase


def update_all(ssn) -> None:
    for job in ssn.jobs.values():
        pg = job.podgroup
        running = sum(1 for t in job.tasks.values()
                      if t.status == TaskStatus.RUNNING)
        succeeded = sum(1 for t in job.tasks.values()
                        if t.status == TaskStatus.SUCCEEDED)
        failed = sum(1 for t in job.tasks.values()
                     if t.status == TaskStatus.FAILED)
        new_phase = _phase_for(job, ssn.uid)
        changed = (pg.running != running or pg.succeeded != succeeded
                   or pg.failed != failed or pg.phase != new_phase
                   or pg.conditions_dirty)
        if not changed:
            continue
        pg.running, pg.succeeded, pg.failed = running, succeeded, failed
        pg.phase = new_phase
        pg.conditions_dirty = False
        ssn.cache.update_job_status(job)
