"""Per-plugin argument map with typed getters.

Mirrors /root/reference/pkg/scheduler/framework/arguments.go:1-99.
"""

from __future__ import annotations

from typing import Dict, Optional


class Arguments(dict):
    """map[string]string with GetBool/GetInt/GetFloat helpers."""

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        return str(v).strip().lower() in ("1", "t", "true", "yes", "y")

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        if v is None:
            return default
        try:
            return int(str(v).strip())
        except ValueError:
            return default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        if v is None:
            return default
        try:
            return float(str(v).strip())
        except ValueError:
            return default
