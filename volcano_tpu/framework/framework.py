"""OpenSession/CloseSession (mirrors
/root/reference/pkg/scheduler/framework/framework.go:30-60)."""

from __future__ import annotations

import gc
import threading
import weakref
from typing import List

from .. import metrics
from ..obs import trace as obs_trace
from .conf import Configuration, Tier
from .registry import get_plugin_builder
from .session import Session


# Whether automatic GC is on in this process OUTSIDE session windows.
# Learned (not snapshotted per session) so an open_session that was never
# paired with close_session — or that died mid-open — cannot latch the
# "disabled" state into every later session's restore decision.
_GC_ON_OUTSIDE: bool = gc.isenabled()

# Suspension DEPTH, not a boolean latch: overlapping session windows
# (controller threads opening an inner session while the scheduler's is
# live, or a plugin opening a nested probe session) each open one
# _GCWindow on suspend and close it on resume, and collection re-enables
# only when the last open window closes — an inner close_session can no
# longer re-enable GC in the middle of the outer session's cycle. Each
# window closes AT MOST ONCE (resume is idempotent per window), so a
# double close_session or a late-firing leak finalizer cannot steal
# another live session's suspension. A session that is never closed
# cannot pin collection off forever either: open_session attaches a
# weakref finalizer that closes the leaked window when the session object
# itself dies (refcount collection still runs while automatic GC is off).
_GC_LOCK = threading.Lock()
_GC_OPEN_WINDOWS: List["_GCWindow"] = []


class _GCWindow:
    __slots__ = ("closed",)

    def __init__(self):
        self.closed = False


def _gc_suspend() -> "_GCWindow":
    global _GC_ON_OUTSIDE
    window = _GCWindow()
    with _GC_LOCK:
        if not _GC_OPEN_WINDOWS and gc.isenabled():
            _GC_ON_OUTSIDE = True
        _GC_OPEN_WINDOWS.append(window)
        gc.disable()
    return window


def _gc_resume(window: "_GCWindow" = None) -> None:
    """Close one suspension window; no-op if that window already closed.
    ``window=None`` (legacy direct callers) closes the most recent open
    window, and is a no-op when none is open."""
    collect = False
    with _GC_LOCK:
        if window is None:
            window = _GC_OPEN_WINDOWS[-1] if _GC_OPEN_WINDOWS else None
        if window is None or window.closed:
            return
        window.closed = True
        try:
            _GC_OPEN_WINDOWS.remove(window)
        except ValueError:       # pragma: no cover - closed implies present
            pass
        if _GC_OPEN_WINDOWS or not _GC_ON_OUTSIDE:
            return
        gc.enable()
        collect = True
    if collect:
        gc.collect(1)


def open_session(cache, tiers: List[Tier],
                 configurations: List[Configuration] = (),
                 time_fn=None, speculative: bool = False) -> Session:
    # Automatic (threshold-triggered) garbage collection is suspended for
    # the lifetime of the session: a cycle at 10k pods allocates enough
    # tracked objects (Resources, task clones, statement entries) to trip
    # gen-1/gen-2 collections mid-action, and a full-heap scan of the
    # session graph costs ~100ms+ of latency noise INSIDE the scheduling
    # cycle (measured: the fused replay phase alternated 125ms/250ms run
    # to run). The reference has no analogue only because Go's GC is
    # concurrent; here the cycle boundary is the idiomatic collection
    # point. close_session resumes collection and runs one bounded
    # young-gen pass to reclaim cycle garbage.
    # suspended BEFORE the Session builds (not just before plugins open):
    # the snapshot inside Session.__init__ is the cycle's biggest allocation
    # burst, and a gen-2 collection tripping mid-clone was half the
    # cold-open jitter (measured: 116ms -> 380ms snapshot swings with
    # automatic GC live)
    # SPECULATIVE open (docs/performance.md pipelining): same plugin
    # lifecycle and its OWN nested GC window — it must consume neither
    # the real session's window nor its plugin callbacks — but the
    # snapshot is the cache's read-only STAGED build, so dirty sets,
    # clone maps and epoch stay untouched until the pipelined shell
    # either adopts (promotion) or discards the speculation.
    window = _gc_suspend()
    try:
        if speculative:
            with obs_trace.span("snapshot", speculative=True):
                ci, basis = cache.speculative_snapshot()
                ssn = Session(cache, tiers, list(configurations),
                              time_fn=time_fn, snapshot=ci)
                ssn.speculative = True
                ssn.spec_basis = basis
        else:
            with obs_trace.span("snapshot"):
                ssn = Session(cache, tiers, list(configurations),
                              time_fn=time_fn)
        for tier in tiers:
            for opt in tier.plugins:
                builder = get_plugin_builder(opt.name)
                if builder is None:
                    continue
                plugin = builder(opt.arguments)
                ssn.plugins[plugin.name()] = plugin
                # the span both records the plugin callback in the cycle
                # trace and feeds the plugin latency histogram — one timer
                with obs_trace.span("plugin:" + plugin.name(),
                                    event="OnSessionOpen") as sp:
                    plugin.on_session_open(ssn)
                metrics.update_plugin_duration(plugin.name(),
                                               "OnSessionOpen", sp.dur_s)
    except BaseException:
        _gc_resume(window)
        raise
    ssn._gc_window = window
    # leak guard: if this session is never close_session'd, close its
    # window when the object dies instead of pinning GC off forever (a
    # no-op if close_session ran — windows close at most once)
    weakref.finalize(ssn, _gc_resume, window)
    return ssn


def _retire_session_pin(ssn: Session) -> None:
    """Release the session's pinned tensor epoch, if any (speculative
    sessions pin one for the in-flight solve). Idempotent."""
    view = getattr(ssn, "_pinned_epoch", None)
    if view is None:
        return
    ssn._pinned_epoch = None
    try:
        view._owner.retire_epoch(view)
    except Exception:  # pragma: no cover - defensive
        pass


def abandon_session(ssn: Session) -> None:
    """Session ROLLBACK path (docs/robustness.md HA section): release the
    session's GC window WITHOUT the close-time writebacks — no plugin
    on_session_close, no podgroup status flush. Used when a leader is
    demoted mid-cycle (the session's decision state must not be
    half-applied by a replica that no longer owns it) and when the
    pipelined shell discards a conflicted speculation. Side effects
    already executed through the cache funnels stand (they carried a
    then-valid fencing epoch); everything session-local is simply
    dropped, including any pinned tensor epoch.
    Idempotent, like close_session's window resume."""
    _retire_session_pin(ssn)
    _gc_resume(getattr(ssn, "_gc_window", None))


def close_session(ssn: Session) -> None:
    try:
        for plugin in ssn.plugins.values():
            with obs_trace.span("plugin:" + plugin.name(),
                                event="OnSessionClose") as sp:
                plugin.on_session_close(ssn)
            metrics.update_plugin_duration(plugin.name(), "OnSessionClose",
                                           sp.dur_s)
        # writeback of job/podgroup status (job_updater.go:95-108)
        from .job_updater import update_all
        with obs_trace.span("job_updater"):
            update_all(ssn)
    finally:
        # idempotent per window: a double close (or the leak finalizer
        # firing later) cannot steal another live session's suspension.
        # Sessions not built by open_session carry no window — legacy
        # most-recent-window resume. A promoted speculative session's
        # pin is normally retired at commit; this is the leak backstop.
        _retire_session_pin(ssn)
        _gc_resume(getattr(ssn, "_gc_window", None))
