"""OpenSession/CloseSession (mirrors
/root/reference/pkg/scheduler/framework/framework.go:30-60)."""

from __future__ import annotations

import time
from typing import List

from .. import metrics
from .conf import Configuration, Tier
from .registry import get_plugin_builder
from .session import Session


def open_session(cache, tiers: List[Tier],
                 configurations: List[Configuration] = ()) -> Session:
    ssn = Session(cache, tiers, list(configurations))
    for tier in tiers:
        for opt in tier.plugins:
            builder = get_plugin_builder(opt.name)
            if builder is None:
                continue
            plugin = builder(opt.arguments)
            ssn.plugins[plugin.name()] = plugin
            start = time.perf_counter()
            plugin.on_session_open(ssn)
            metrics.update_plugin_duration(plugin.name(), "OnSessionOpen",
                                           time.perf_counter() - start)
    return ssn


def close_session(ssn: Session) -> None:
    for plugin in ssn.plugins.values():
        start = time.perf_counter()
        plugin.on_session_close(ssn)
        metrics.update_plugin_duration(plugin.name(), "OnSessionClose",
                                       time.perf_counter() - start)
    # writeback of job/podgroup status (job_updater.go:95-108)
    from .job_updater import update_all
    update_all(ssn)
