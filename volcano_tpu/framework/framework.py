"""OpenSession/CloseSession (mirrors
/root/reference/pkg/scheduler/framework/framework.go:30-60)."""

from __future__ import annotations

import gc
import time
from typing import List

from .. import metrics
from .conf import Configuration, Tier
from .registry import get_plugin_builder
from .session import Session


# Whether automatic GC is on in this process OUTSIDE session windows.
# Learned (not snapshotted per session) so an open_session that was never
# paired with close_session — or that died mid-open — cannot latch the
# "disabled" state into every later session's restore decision.
_GC_ON_OUTSIDE: bool = gc.isenabled()


def _gc_suspend() -> None:
    global _GC_ON_OUTSIDE
    if gc.isenabled():
        _GC_ON_OUTSIDE = True
    gc.disable()


def _gc_resume() -> None:
    if _GC_ON_OUTSIDE:
        gc.enable()
        gc.collect(1)


def open_session(cache, tiers: List[Tier],
                 configurations: List[Configuration] = ()) -> Session:
    # Automatic (threshold-triggered) garbage collection is suspended for
    # the lifetime of the session: a cycle at 10k pods allocates enough
    # tracked objects (Resources, task clones, statement entries) to trip
    # gen-1/gen-2 collections mid-action, and a full-heap scan of the
    # session graph costs ~100ms+ of latency noise INSIDE the scheduling
    # cycle (measured: the fused replay phase alternated 125ms/250ms run
    # to run). The reference has no analogue only because Go's GC is
    # concurrent; here the cycle boundary is the idiomatic collection
    # point. close_session resumes collection and runs one bounded
    # young-gen pass to reclaim cycle garbage.
    ssn = Session(cache, tiers, list(configurations))
    _gc_suspend()
    try:
        for tier in tiers:
            for opt in tier.plugins:
                builder = get_plugin_builder(opt.name)
                if builder is None:
                    continue
                plugin = builder(opt.arguments)
                ssn.plugins[plugin.name()] = plugin
                start = time.perf_counter()
                plugin.on_session_open(ssn)
                metrics.update_plugin_duration(plugin.name(), "OnSessionOpen",
                                               time.perf_counter() - start)
    except BaseException:
        _gc_resume()
        raise
    return ssn


def close_session(ssn: Session) -> None:
    try:
        for plugin in ssn.plugins.values():
            start = time.perf_counter()
            plugin.on_session_close(ssn)
            metrics.update_plugin_duration(plugin.name(), "OnSessionClose",
                                           time.perf_counter() - start)
        # writeback of job/podgroup status (job_updater.go:95-108)
        from .job_updater import update_all
        update_all(ssn)
    finally:
        _gc_resume()
