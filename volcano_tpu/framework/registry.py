"""Plugin and action registries.

Mirrors /root/reference/pkg/scheduler/framework/plugins.go:38-119. The
reference loads custom plugins from ``.so`` files via Go's plugin.Open; the
Python-native equivalent loads modules from a ``--plugins-dir`` (each module
exposes ``New(arguments)``) or from installed entry points.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_plugin_builders: Dict[str, Callable] = {}
_actions: Dict[str, object] = {}


def register_plugin_builder(name: str, builder: Callable) -> None:
    with _lock:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[Callable]:
    with _lock:
        return _plugin_builders.get(name)


def register_action(action) -> None:
    with _lock:
        _actions[action.name()] = action


def get_action(name: str):
    with _lock:
        return _actions.get(name)


def load_custom_plugins(plugins_dir: str) -> None:
    """Load every ``*.py`` in plugins_dir; each must define ``New(arguments)``
    returning a plugin, registered under the module basename
    (the analogue of plugins.go:62-99)."""
    for fname in sorted(os.listdir(plugins_dir)):
        if not fname.endswith(".py"):
            continue
        name = fname[:-3]
        path = os.path.join(plugins_dir, fname)
        spec = importlib.util.spec_from_file_location(f"vtpu_custom_{name}", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        if not hasattr(mod, "New"):
            raise ValueError(f"custom plugin {path} lacks New(arguments)")
        register_plugin_builder(name, mod.New)
