"""Scheduler configuration schema + YAML parsing.

Identical YAML schema to the reference so configs are a drop-in swap:
``actions`` comma string, ``tiers[].plugins[]`` with per-plugin enable flags
and arguments, per-action ``configurations`` blocks
(/root/reference/pkg/scheduler/conf/scheduler_conf.go:20-86, parsing
pkg/scheduler/util.go:44-92).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml

from .arguments import Arguments

DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

# The per-plugin enable flags: YAML tag (exactly as the reference's struct
# tags, scheduler_conf.go:45-81) -> internal flag name used by the session's
# tier dispatch. Missing flag means enabled.
ENABLE_FLAG_TAGS = {
    "enableJobOrder": "enabledJobOrder",
    "enableNamespaceOrder": "enabledNamespaceOrder",
    "enableHierarchy": "enabledHierarchy",
    "enableJobReady": "enabledJobReady",
    "enableJobPipelined": "enabledJobPipelined",
    "enableTaskOrder": "enabledTaskOrder",
    "enablePreemptable": "enabledPreemptable",
    "enableReclaimable": "enabledReclaimable",
    "enableQueueOrder": "enabledQueueOrder",
    "EnabledClusterOrder": "enabledClusterOrder",   # sic — reference tag
    "enablePredicate": "enabledPredicate",
    "enableBestNode": "enabledBestNode",
    "enableNodeOrder": "enabledNodeOrder",
    "enableTargetJob": "enabledTargetJob",
    "enableReservedNodes": "enabledReservedNodes",
    "enableJobEnqueued": "enabledJobEnqueued",
    "enabledVictim": "enabledVictim",               # sic — reference tag
    "enableJobStarving": "enabledJobStarving",
}
# internal names are also accepted as YAML keys for convenience
ENABLE_FLAG_TAGS.update({v: v for v in list(ENABLE_FLAG_TAGS.values())})


@dataclass
class PluginOption:
    name: str
    enabled: Dict[str, bool] = field(default_factory=dict)
    arguments: Arguments = field(default_factory=Arguments)

    def is_enabled(self, flag: str) -> bool:
        return self.enabled.get(flag, True)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class Configuration:
    """Per-action arguments block (conf/scheduler_conf.go Configurations)."""

    name: str
    arguments: Arguments = field(default_factory=Arguments)


@dataclass
class SchedulerConfiguration:
    actions: List[str] = field(default_factory=list)
    tiers: List[Tier] = field(default_factory=list)
    configurations: List[Configuration] = field(default_factory=list)

    def action_arguments(self, action: str) -> Arguments:
        for c in self.configurations:
            if c.name == action:
                return c.arguments
        return Arguments()


def parse_scheduler_conf(text: Optional[str] = None) -> SchedulerConfiguration:
    """Parse the scheduler YAML; None/empty falls back to the default conf
    (pkg/scheduler/util.go:31-42)."""
    raw = yaml.safe_load(text) if text else None
    if not raw:
        raw = yaml.safe_load(DEFAULT_SCHEDULER_CONF)

    actions = [a.strip() for a in str(raw.get("actions", "")).split(",") if a.strip()]

    tiers: List[Tier] = []
    for tier_raw in raw.get("tiers") or []:
        plugins = []
        for p in tier_raw.get("plugins") or []:
            enabled = {ENABLE_FLAG_TAGS[k]: bool(v) for k, v in p.items()
                       if k in ENABLE_FLAG_TAGS}
            args = Arguments(p.get("arguments") or {})
            plugins.append(PluginOption(name=p["name"], enabled=enabled,
                                        arguments=args))
        tiers.append(Tier(plugins=plugins))

    configurations = [
        Configuration(name=c["name"], arguments=Arguments(c.get("arguments") or {}))
        for c in raw.get("configurations") or []
    ]
    return SchedulerConfiguration(actions=actions, tiers=tiers,
                                  configurations=configurations)
