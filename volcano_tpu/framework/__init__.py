"""Scheduler framework: Session, Statement, tiers, conf, registries."""

from .arguments import Arguments
from .conf import (DEFAULT_SCHEDULER_CONF, Configuration, PluginOption,
                   SchedulerConfiguration, Tier, parse_scheduler_conf)
from .framework import abandon_session, close_session, open_session
from .registry import (get_action, get_plugin_builder, load_custom_plugins,
                       register_action, register_plugin_builder)
from .session import (ABSTAIN, PERMIT, REJECT, Event, EventHandler, Session,
                      ValidateResult)
from .statement import Statement

__all__ = [
    "Arguments", "DEFAULT_SCHEDULER_CONF", "Configuration", "PluginOption",
    "SchedulerConfiguration", "Tier", "parse_scheduler_conf",
    "abandon_session", "close_session", "open_session",
    "get_action", "get_plugin_builder", "load_custom_plugins",
    "register_action", "register_plugin_builder",
    "ABSTAIN", "PERMIT", "REJECT", "Event", "EventHandler", "Session",
    "ValidateResult", "Statement",
]
