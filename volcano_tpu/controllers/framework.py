"""Controller framework: interface + registry.

Mirrors /root/reference/pkg/controllers/framework/{interface.go:25-43,
factory.go:24-43}.
"""

from __future__ import annotations

from typing import Callable, Dict

_controllers: Dict[str, Callable] = {}


class Controller:
    NAME = "controller"

    def name(self) -> str:
        return self.NAME

    def initialize(self, store, **options) -> None:
        raise NotImplementedError

    def run(self) -> None:
        """Register watches; in-process controllers are event-driven so run
        is synchronous wiring, not a goroutine loop."""


def register_controller(builder: Callable) -> None:
    _controllers[builder().NAME if hasattr(builder, "NAME") else str(builder)] = builder


def foreach_controller(fn: Callable) -> None:
    for builder in _controllers.values():
        fn(builder)
