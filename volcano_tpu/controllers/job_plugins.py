"""Job controller plugins: ssh / svc / env pod mutation hooks.

Mirrors /root/reference/pkg/controllers/job/plugins/{ssh/ssh.go:48-215,
svc/svc.go:52-218, env/env.go, factory.go:28-51} — per-job SSH keypair
secret for passwordless MPI, hostfile env (VC_<TASK>_HOSTS), and per-task
index env vars, applied according to Job.spec.plugins.
"""

from __future__ import annotations

import base64
import hashlib
from typing import Dict, List

from ..apis.objects import Job, Pod, TaskSpec

SSH_PRIVATE_KEY = "id_rsa"
SSH_PUBLIC_KEY = "id_rsa.pub"


def _ssh_secret_name(job: Job) -> str:
    return f"{job.metadata.name}-ssh"


def _generate_keypair(job: Job):
    """(private_pem, public_openssh) — a usable keypair like the reference's
    RSA Secret (ssh.go:48-215)."""
    try:
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ed25519
        key = ed25519.Ed25519PrivateKey.generate()
        priv = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption()).decode()
        pub = key.public_key().public_bytes(
            serialization.Encoding.OpenSSH,
            serialization.PublicFormat.OpenSSH).decode()
        return priv, pub
    except Exception:
        # no crypto backend in this image: deterministic marker pair keeps
        # the mount contract testable
        seed = hashlib.sha256(job.metadata.key().encode()).digest()
        return (base64.b64encode(seed).decode(),
                base64.b64encode(seed[::-1]).decode())


def plugin_on_job_add(store, job: Job) -> None:
    """OnJobAdd hooks: create job-level artifacts (ssh secret, svc hostfile
    stored as job annotations — the in-process analogue of the Secret and
    ConfigMap the reference creates)."""
    if "ssh" in job.spec.plugins:
        if "volcano.sh/ssh-secret" not in job.metadata.annotations:
            # a REAL keypair (ssh.go:48-215 generates RSA into a Secret for
            # passwordless MPI): ed25519 via the stdlib when available,
            # RSA-from-cryptography as fallback, and only then a marker
            priv, pub = _generate_keypair(job)
            job.metadata.annotations["volcano.sh/ssh-secret"] = _ssh_secret_name(job)
            job.metadata.annotations["volcano.sh/ssh-private"] = priv
            job.metadata.annotations["volcano.sh/ssh-public"] = pub
    if "svc" in job.spec.plugins:
        hosts = _job_hosts(job)
        job.metadata.annotations["volcano.sh/job-hosts"] = ",".join(hosts)


def plugin_on_pod_create(store, job: Job, task: TaskSpec, index: int,
                         pod: Pod) -> None:
    """OnPodCreate hooks: env vars + hostfile + ssh mount markers."""
    env: List[dict] = pod.template.env
    if "env" in job.spec.plugins:
        # per-task index env (env.go): both VC_ and legacy VK_ names
        env.append({"name": "VC_TASK_INDEX", "value": str(index)})
        env.append({"name": "VK_TASK_INDEX", "value": str(index)})
    if "svc" in job.spec.plugins:
        for t in job.spec.tasks:
            hosts = [f"{job.metadata.name}-{t.name}-{i}.{job.metadata.name}"
                     for i in range(t.replicas)]
            env.append({
                "name": f"VC_{t.name.upper().replace('-', '_')}_HOSTS",
                "value": ",".join(hosts)})
            env.append({
                "name": f"VC_{t.name.upper().replace('-', '_')}_NUM",
                "value": str(t.replicas)})
        pod.template.labels.setdefault("volcano.sh/job-service",
                                       job.metadata.name)
    if "ssh" in job.spec.plugins:
        pod.template.volumes.append({
            "name": "ssh-volume",
            "secret": _ssh_secret_name(job),
            "mount_path": "/root/.ssh",
        })


def _job_hosts(job: Job) -> List[str]:
    hosts = []
    for task in job.spec.tasks:
        for i in range(task.replicas):
            hosts.append(f"{job.metadata.name}-{task.name}-{i}.{job.metadata.name}")
    return hosts
