"""PodGroup controller: auto-gang for bare pods.

Mirrors /root/reference/pkg/controllers/podgroup/pg_controller_handler.go:
37-127 — a plain pod with the volcano scheduler and no group annotation gets
a 1-member PodGroup and the annotation stamped.
"""

from __future__ import annotations

from ..apis.objects import ObjectMeta, Pod, PodGroupCR, PodGroupSpec
from ..cache.store_wiring import GROUP_NAME_ANNOTATION
from ..store import ADDED, ObjectStore
from .framework import Controller


class PodGroupController(Controller):
    NAME = "pg-controller"

    def __init__(self, scheduler_name: str = "volcano"):
        self.store: ObjectStore = None
        self.scheduler_name = scheduler_name

    def initialize(self, store: ObjectStore, **options) -> None:
        self.store = store
        store.watch("Pod", self._on_pod)

    def _on_pod(self, event: str, pod: Pod, old) -> None:
        if event != ADDED:
            return
        if pod.scheduler_name != self.scheduler_name:
            return
        if pod.metadata.annotations.get(GROUP_NAME_ANNOTATION):
            return
        pg_name = f"podgroup-{pod.metadata.uid}"
        if self.store.get("PodGroup", pod.metadata.namespace, pg_name) is None:
            self.store.create(PodGroupCR(
                metadata=ObjectMeta(
                    name=pg_name, namespace=pod.metadata.namespace,
                    owner_references=[{"kind": "Pod",
                                       "name": pod.metadata.name}]),
                spec=PodGroupSpec(
                    min_member=1,
                    queue=pod.metadata.annotations.get(
                        "volcano.sh/queue-name", "default"),
                    min_resources=(pod.template.resources.clone()
                                   if pod.template.resources else None))))
        pod.metadata.annotations[GROUP_NAME_ANNOTATION] = pg_name
        self.store.update(pod)
