"""Job controller: the Job CR lifecycle engine.

Mirrors /root/reference/pkg/controllers/job/{job_controller.go:118-218,
job_controller_actions.go:43-660, job_controller_handler.go:137-400} —
informers on Job/Pod/Command, syncJob (podgroup + pod diff create/delete),
killJob, lifecycle-policy event→action dispatch, and the job plugins
(ssh/svc/env) that mutate pods at creation.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..api import BusAction, BusEvent, JobPhase, PodGroupPhase, Resource
from ..apis.objects import (Command, Job, LifecyclePolicy, ObjectMeta, PVC,
                            Pod, PodGroupCR, PodGroupSpec, PodTemplate,
                            TaskSpec)
from ..cache.store_wiring import GROUP_NAME_ANNOTATION
from ..store import ADDED, DELETED, UPDATED, AdmissionError, ObjectStore
from . import job_state
from .framework import Controller
from .job_plugins import plugin_on_job_add, plugin_on_pod_create

TASK_SPEC_ANNOTATION = "volcano.sh/task-spec"
JOB_NAME_ANNOTATION = "volcano.sh/job-name"
TASK_INDEX_ANNOTATION = "volcano.sh/task-index"


def pod_name(job: Job, task: TaskSpec, index: int) -> str:
    return f"{job.metadata.name}-{task.name}-{index}"


def calc_pg_min_resources(job: Job) -> Resource:
    """Sum of the first minAvailable pod requests, tasks in priority order
    (job_controller_actions.go:638-660). Runs on every job sync, so it
    stops at minAvailable instead of materializing all replicas."""
    total = Resource()
    left = job.spec.min_available
    for task in sorted(job.spec.tasks, key=lambda t: -t.template.priority):
        if left <= 0:
            break
        take = min(left, task.replicas)
        r = task.template.resources or Resource()
        for _ in range(take):
            total.add(r)
        left -= take
    return total


class JobController(Controller):
    NAME = "job-controller"

    def __init__(self):
        self.store: ObjectStore = None
        self._lock = threading.RLock()
        # per-job reentrancy guard: a sync writes Job/PodGroup status, whose
        # watch events must not re-enter the same job's state machine (the
        # reference's workqueue naturally dedups; in-process events are
        # synchronous)
        self._in_execute: set = set()
        # jobs with churned pods awaiting a coalesced sync (the workqueue)
        self._dirty: set = set()
        # last observed PodGroup phase per job, for Unknown-transition
        # detection (status writes mutate in place, so watch `old` lies)
        self._pg_phases: dict = {}

    # -- wiring -------------------------------------------------------------

    def initialize(self, store: ObjectStore, **options) -> None:
        self.store = store
        job_state.sync_job = self.sync_job
        job_state.kill_job = self.kill_job
        store.watch("Job", self._on_job)
        store.watch("Pod", self._on_pod)
        store.watch("Command", self._on_command)
        store.watch("PodGroup", self._on_podgroup)
        store.watch("PersistentVolumeClaim", self._on_pvc)

    def _on_job(self, event: str, job: Job, old) -> None:
        if event == ADDED:
            self._execute(job, BusAction.SYNC_JOB)
        elif event == UPDATED:
            # value comparison, not identity: stores that serialize (the
            # native C++ store, a real API server) deliver copies, and a
            # status-only write must not re-trigger sync (handler.go
            # updateJob only reacts to spec changes)
            if old is not None and old.spec != job.spec:
                self._execute(job, BusAction.SYNC_JOB)
        elif event == DELETED:
            self._delete_job_resources(job)

    def _on_pod(self, event: str, pod: Pod, old) -> None:
        job_name = pod.metadata.annotations.get(JOB_NAME_ANNOTATION)
        if not job_name:
            return
        job = self.store.get("Job", pod.metadata.namespace, job_name)
        if job is None:
            return
        bus_event = None
        if event == UPDATED and old is not None:
            if pod.status.phase == "Failed" and old.status.phase != "Failed":
                bus_event = BusEvent.POD_FAILED
            elif (pod.status.phase == "Succeeded"
                  and old.status.phase != "Succeeded"):
                bus_event = BusEvent.TASK_COMPLETED
        elif event == DELETED:
            if pod.status.conditions and any(
                    c.get("type") == "Evicted" for c in pod.status.conditions):
                bus_event = BusEvent.POD_EVICTED
            elif pod.status.phase not in ("Succeeded", "Failed"):
                bus_event = BusEvent.POD_EVICTED
        if bus_event is None:
            # plain churn (creates, phase flips to Running, drains): mark
            # dirty and coalesce — the reference's sharded workqueue dedups
            # job keys exactly like this; syncing per pod event is O(pods^2)
            # at 10k pods
            with self._lock:
                self._dirty.add((pod.metadata.namespace, job_name))
            return
        action = self._policy_action(job, pod, bus_event)
        self._execute(job, action)

    def process_dirty(self) -> int:
        """Sync every job whose pods churned since the last drain — called
        by the controller loop each scheduler period (the workqueue worker
        analogue, job_controller.go:256+)."""
        with self._lock:
            dirty = list(self._dirty)
            self._dirty.clear()
        for ns, name in dirty:
            job = self.store.get("Job", ns, name)
            if job is not None:
                self._execute(job, BusAction.SYNC_JOB)
        return len(dirty)

    def _policy_action(self, job: Job, pod: Pod,
                       event: Optional[BusEvent]) -> BusAction:
        """LifecyclePolicy events→actions (handler.go:137-351,
        job_controller_util.go:170-200): task policies override job
        policies; an exitCode policy matches the pod's termination code,
        an event policy the bus event; default SyncJob."""
        if event is None:
            return BusAction.SYNC_JOB
        exit_code = pod.status.exit_code

        def matches(policy) -> bool:
            # two INDEPENDENT checks (applyPolicies
            # job_controller_util.go:168-200): the event clause when the
            # policy has one, the exit-code clause when it has one —
            # admission guarantees a policy carries exactly one of them
            if policy.event is not None \
                    and policy.event in (event, BusEvent.ANY):
                return True
            return (policy.exit_code is not None and exit_code is not None
                    and exit_code == policy.exit_code)

        task_name = pod.metadata.annotations.get(TASK_SPEC_ANNOTATION, "")
        for task in job.spec.tasks:
            if task.name == task_name:
                for policy in task.policies:
                    if matches(policy):
                        return policy.action
        for policy in job.spec.policies:
            if matches(policy):
                return policy.action
        return BusAction.SYNC_JOB

    def _on_podgroup(self, event: str, pg, old) -> None:
        """Re-sync the owning job whenever its PodGroup is schedulable —
        pods are only created once the group left Pending
        (job_controller_actions.go:263-280 syncTask gate). Status writes
        mutate in place, so `old` cannot be trusted for transition
        detection; the sync is idempotent (desired-vs-existing pod diff)."""
        if event == DELETED:
            self._pg_phases.pop((pg.metadata.namespace, pg.metadata.name),
                                None)
            return
        if event != UPDATED:
            return
        if pg.status.phase == PodGroupPhase.PENDING:
            return
        job = self.store.get("Job", pg.metadata.namespace, pg.metadata.name)
        if job is None:
            return
        # a PodGroup turning Unknown (running members + a fresh
        # Unschedulable condition: the gang split) raises the JobUnknown
        # bus event against the job's lifecycle policies
        # (job_controller_handler.go:405-433); transition-tracked here
        # because status writes mutate in place
        key = (pg.metadata.namespace, pg.metadata.name)
        prev_phase = self._pg_phases.get(key)
        self._pg_phases[key] = pg.status.phase
        if (pg.status.phase == PodGroupPhase.UNKNOWN
                and prev_phase != PodGroupPhase.UNKNOWN):
            action = self._unknown_policy_action(job)
            if action != BusAction.SYNC_JOB:
                self._execute(job, action)
                return
        # only sync when pods are actually missing — sync_job itself writes
        # the PodGroup status, so an unconditional trigger would recurse
        desired = sum(t.replicas for t in job.spec.tasks)
        existing = sum(
            1 for p in self.store.list("Pod", job.metadata.namespace)
            if p.metadata.annotations.get(JOB_NAME_ANNOTATION)
            == job.metadata.name)
        if existing < desired:
            self._execute(job, BusAction.SYNC_JOB)

    def _unknown_policy_action(self, job: Job) -> BusAction:
        for policy in job.spec.policies:
            if policy.event in (BusEvent.JOB_UNKNOWN, BusEvent.ANY):
                return policy.action
        return BusAction.SYNC_JOB

    def _on_pvc(self, event: str, pvc, old) -> None:
        """A job waiting on a referenced-but-missing PVC re-syncs when it
        appears (the reference's pvc informer + error resync)."""
        if event != ADDED:
            return
        for job in self.store.list("Job", pvc.metadata.namespace):
            if any(v.get("volumeClaimName") == pvc.metadata.name
                   for v in job.spec.volumes):
                self._execute(job, BusAction.SYNC_JOB)

    def _on_command(self, event: str, cmd: Command, old) -> None:
        """Command CR → state-machine action (handler.go:364-400)."""
        if event != ADDED:
            return
        target = cmd.target_object or {}
        if target.get("kind") != "Job":
            return
        job = self.store.get("Job", cmd.metadata.namespace, target.get("name"))
        self.store.delete("Command", cmd.metadata.namespace, cmd.metadata.name)
        if job is None:
            return
        self._execute(job, cmd.action)
        self.store.update_status(job)
        # a Resume lands in Restarting; drive the restart chain
        # (drain -> Pending -> resync) like the reference's requeue
        if job.status.state == JobPhase.RESTARTING:
            self._execute(job, BusAction.SYNC_JOB)

    def _execute(self, job: Job, action: BusAction) -> None:
        # keyed by (job, action, phase): a sync's own status writes must not
        # re-enter the same state, while a nested execute after a genuine
        # phase transition (e.g. Restarting -> Pending resync) proceeds
        key = (job.metadata.namespace, job.metadata.name, action,
               job.status.state)
        with self._lock:
            if key in self._in_execute:
                return
            self._in_execute.add(key)
            try:
                job_state.new_state(job).execute(action)
            finally:
                self._in_execute.discard(key)

    # -- core sync (job_controller_actions.go:206-440) -----------------------

    def sync_job(self, job: Job, next_phase: Callable) -> None:
        if job.status.state in (JobPhase.COMPLETED, JobPhase.FAILED,
                                JobPhase.TERMINATED, JobPhase.ABORTED):
            return
        io_ok = self._initiate_job(job)
        desired: Dict[str, tuple] = {}
        for task in job.spec.tasks:
            for i in range(task.replicas):
                desired[pod_name(job, task, i)] = (task, i)

        existing = {p.metadata.name: p
                    for p in self.store.list("Pod", job.metadata.namespace)
                    if p.metadata.annotations.get(JOB_NAME_ANNOTATION)
                    == job.metadata.name}

        # syncTask gate (job_controller_actions.go:263-280): create pods
        # only once the PodGroup left Pending (the scheduler's enqueue
        # admitted it); the /pods webhook rejects earlier creations
        pg = self.store.get("PodGroup", job.metadata.namespace,
                            job.metadata.name)
        sync_task = io_ok and pg is not None and \
            pg.status.phase != PodGroupPhase.PENDING
        if sync_task:
            for name, (task, i) in desired.items():
                if name not in existing:
                    self._create_pod(job, task, i)
            for name, pod in existing.items():
                if name not in desired:
                    self.store.delete("Pod", job.metadata.namespace, name)

        self._update_status(job)
        prev_state = job.status.state
        job_state._update_phase(job, next_phase(job.status))
        self.store.update_status(job)
        self._sync_podgroup_phase(job)
        # entering a finished phase runs the Finished state once (the
        # reference requeues the job after the status write): finished.go:30
        # drains straggler pods with the Soft retain set
        if job.status.state in (JobPhase.COMPLETED, JobPhase.FAILED,
                                JobPhase.TERMINATED) \
                and job.status.state != prev_state:
            self._execute(job, BusAction.SYNC_JOB)

    def kill_job(self, job: Job, phase: JobPhase,
                 transition: Optional[Callable] = None,
                 retain_phases: tuple = ()) -> None:
        """Delete the job's pods except those in ``retain_phases``, then
        transition (job_controller_actions.go:43-146: PodRetainPhaseSoft
        keeps Succeeded/Failed pods on abort/terminate/complete;
        PodRetainPhaseNone on restart drains everything)."""
        job_state._update_phase(job, phase)
        for pod in self.store.list("Pod", job.metadata.namespace):
            if pod.metadata.annotations.get(JOB_NAME_ANNOTATION) \
                    == job.metadata.name \
                    and pod.status.phase not in retain_phases:
                self.store.delete("Pod", job.metadata.namespace,
                                  pod.metadata.name)
        self._update_status(job)
        if transition is not None:
            job_state._update_phase(job, transition(job.status))
        self.store.update_status(job)
        # restart cycle continues: once drained, Restarting -> Pending resync
        if job.status.state == JobPhase.PENDING:
            self._execute(job, BusAction.SYNC_JOB)

    def _create_job_io_if_not_exist(self, job: Job) -> bool:
        """PVC lifecycle (createJobIOIfNotExist,
        job_controller_actions.go:442-494): generate claim names, create
        owned PVCs from volumeClaim specs, require referenced PVCs to
        exist — a missing one keeps the job Pending until it appears."""
        ok = True
        for i, volume in enumerate(job.spec.volumes):
            vc_name = volume.get("volumeClaimName", "")
            if not vc_name:
                n = 0
                while True:
                    vc_name = f"{job.metadata.name}-pvc-{i}-{n}"
                    if self.store.get("PersistentVolumeClaim",
                                      job.metadata.namespace,
                                      vc_name) is None:
                        break
                    n += 1
                volume["volumeClaimName"] = vc_name
                if volume.get("volumeClaim") is not None:
                    self.store.create(PVC(
                        metadata=ObjectMeta(
                            name=vc_name,
                            namespace=job.metadata.namespace,
                            owner_references=[{"kind": "Job",
                                               "name": job.metadata.name}]),
                        spec=dict(volume.get("volumeClaim") or {})))
                self.store.update(job)
            elif self.store.get("PersistentVolumeClaim",
                                job.metadata.namespace, vc_name) is None:
                job.status.state_message = (
                    f"pvc {vc_name} is not found, the job will be in the "
                    f"Pending state until the PVC is created")
                ok = False
                continue
            job.status.controlled_resources[f"volume-pvc-{vc_name}"] = vc_name
        return ok

    def _initiate_job(self, job: Job) -> bool:
        """Finalizer + PVCs + PodGroup + plugin OnJobAdd
        (job_controller_actions.go:442-560)."""
        if "volcano.sh/job-finalizer" not in job.metadata.finalizers:
            job.metadata.finalizers.append("volcano.sh/job-finalizer")
        io_ok = self._create_job_io_if_not_exist(job)
        plugin_on_job_add(self.store, job)
        pg = self.store.get("PodGroup", job.metadata.namespace,
                            job.metadata.name)
        min_res = calc_pg_min_resources(job)       # runs on EVERY sync
        if pg is None:
            pg = PodGroupCR(
                metadata=ObjectMeta(name=job.metadata.name,
                                    namespace=job.metadata.namespace,
                                    owner_references=[{
                                        "kind": "Job",
                                        "name": job.metadata.name}]),
                spec=PodGroupSpec(
                    min_member=job.spec.min_available,
                    queue=job.spec.queue,
                    priority_class_name=job.spec.priority_class_name,
                    min_resources=min_res))
            self.store.create(pg)
        elif (pg.spec.min_member != job.spec.min_available
              or pg.spec.priority_class_name != job.spec.priority_class_name
              or pg.spec.min_resources != min_res):
            # job_controller_actions.go:530-636 createOrUpdatePodGroup syncs
            # minMember, minResources AND priorityClassName on job updates —
            # minResources must be compared too, or an elastic template
            # change at constant minAvailable never reaches the scheduler's
            # enqueue quota math
            pg.spec.min_member = job.spec.min_available
            pg.spec.min_resources = min_res
            pg.spec.priority_class_name = job.spec.priority_class_name
            self.store.update(pg)
        return io_ok

    def _create_pod(self, job: Job, task: TaskSpec, index: int) -> None:
        import copy
        template = copy.deepcopy(task.template)
        pod = Pod(
            metadata=ObjectMeta(
                name=pod_name(job, task, index),
                namespace=job.metadata.namespace,
                annotations={
                    GROUP_NAME_ANNOTATION: job.metadata.name,
                    JOB_NAME_ANNOTATION: job.metadata.name,
                    TASK_SPEC_ANNOTATION: task.name,
                    TASK_INDEX_ANNOTATION: str(index),
                },
                owner_references=[{"kind": "Job", "name": job.metadata.name}]),
            template=template,
            scheduler_name=job.spec.scheduler_name)
        # mount the job's volumes into every pod (createJobPod's volume
        # wiring, job_controller_util.go)
        for volume in job.spec.volumes:
            vc_name = volume.get("volumeClaimName")
            if vc_name:
                pod.template.volumes.append(
                    {"claimName": vc_name,
                     "mountPath": volume.get("mountPath", "")})
        plugin_on_pod_create(self.store, job, task, index, pod)
        try:
            self.store.create(pod)
        except (ValueError, AdmissionError):
            pass

    def _update_status(self, job: Job) -> None:
        counts = {"Pending": 0, "Running": 0, "Succeeded": 0, "Failed": 0}
        task_counts: Dict[str, Dict[str, int]] = {}
        for pod in self.store.list("Pod", job.metadata.namespace):
            if pod.metadata.annotations.get(JOB_NAME_ANNOTATION) \
                    != job.metadata.name:
                continue
            counts[pod.status.phase] = counts.get(pod.status.phase, 0) + 1
            task = pod.metadata.annotations.get(TASK_SPEC_ANNOTATION, "")
            task_counts.setdefault(task, {}).setdefault(pod.status.phase, 0)
            task_counts[task][pod.status.phase] += 1
        job.status.pending = counts.get("Pending", 0)
        job.status.running = counts.get("Running", 0)
        job.status.succeeded = counts.get("Succeeded", 0)
        job.status.failed = counts.get("Failed", 0)
        job.status.terminating = 0
        job.status.min_available = job.spec.min_available
        job.status.task_status_count = task_counts
        job.status.version += 1

    def _sync_podgroup_phase(self, job: Job) -> None:
        pg = self.store.get("PodGroup", job.metadata.namespace,
                            job.metadata.name)
        if pg is None:
            return
        pg.status.running = job.status.running
        pg.status.succeeded = job.status.succeeded
        pg.status.failed = job.status.failed
        self.store.update_status(pg)

    def _delete_job_resources(self, job: Job) -> None:
        for pod in self.store.list("Pod", job.metadata.namespace):
            if pod.metadata.annotations.get(JOB_NAME_ANNOTATION) \
                    == job.metadata.name:
                self.store.delete("Pod", job.metadata.namespace,
                                  pod.metadata.name)
        self.store.delete("PodGroup", job.metadata.namespace,
                          job.metadata.name)
