"""Garbage collector: TTLSecondsAfterFinished job cleanup.

Mirrors /root/reference/pkg/controllers/garbagecollector/
garbagecollector.go:70-296 — finished jobs past their TTL are deleted after
a freshness re-check.
"""

from __future__ import annotations

import time
from typing import List

from ..api import JobPhase
from ..apis.objects import Job
from ..store import ObjectStore
from .framework import Controller

FINISHED = (JobPhase.COMPLETED, JobPhase.FAILED, JobPhase.TERMINATED,
            JobPhase.ABORTED)


class GarbageCollector(Controller):
    NAME = "gc-controller"

    def __init__(self):
        self.store: ObjectStore = None

    def initialize(self, store: ObjectStore, **options) -> None:
        self.store = store

    def needs_cleanup(self, job: Job, now: float = None) -> bool:
        if job.spec.ttl_seconds_after_finished is None:
            return False
        if job.status.state not in FINISHED:
            return False
        now = now if now is not None else time.time()
        expiry = (job.status.state_last_transition
                  + job.spec.ttl_seconds_after_finished)
        return now >= expiry

    def process(self, now: float = None) -> List[str]:
        """One GC sweep; returns deleted job keys. The reference requeues on
        a timer — callers (tests, the controller-manager loop) drive this."""
        deleted = []
        for job in list(self.store.list("Job")):
            # freshness double-check (garbagecollector.go:200-240)
            fresh = self.store.get("Job", job.metadata.namespace,
                                   job.metadata.name)
            if fresh is None or not self.needs_cleanup(fresh, now):
                continue
            self.store.delete("Job", fresh.metadata.namespace,
                              fresh.metadata.name)
            deleted.append(fresh.metadata.key())
        return deleted
