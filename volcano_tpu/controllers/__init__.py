"""Controllers: job lifecycle, podgroup auto-gang, queue state, GC
(mirrors /root/reference/pkg/controllers)."""

from .framework import Controller
from .garbage_collector import GarbageCollector
from .job_controller import JobController
from .podgroup_controller import PodGroupController
from .queue_controller import QueueController


def start_controllers(store, scheduler_name: str = "volcano"):
    """cmd/controller-manager analogue: initialize every controller against
    the store (server.go:113-130)."""
    controllers = [JobController(), PodGroupController(scheduler_name),
                   QueueController(), GarbageCollector()]
    for c in controllers:
        c.initialize(store)
    return controllers


__all__ = ["Controller", "GarbageCollector", "JobController",
           "PodGroupController", "QueueController", "start_controllers"]
