"""Job state machine.

Mirrors /root/reference/pkg/controllers/job/state/{factory.go:28-86,
pending.go, running.go:30-60, restarting.go, aborting.go, completing.go,
terminating.go, finished.go} — per-phase State objects transitioning on bus
Actions, with SyncJob/KillJob injected by the controller.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..api import BusAction, JobPhase
from ..apis.objects import Job

# Injected by the job controller (state/factory.go:48-53).
sync_job: Callable = None
kill_job: Callable = None

# state/factory.go:39-44: PodRetainPhaseNone drains everything (restart);
# PodRetainPhaseSoft keeps finished pods (abort/terminate/complete)
POD_RETAIN_PHASE_NONE = ()
POD_RETAIN_PHASE_SOFT = ("Succeeded", "Failed")


class State:
    def __init__(self, job: Job):
        self.job = job

    def execute(self, action: BusAction) -> None:
        raise NotImplementedError


def _update_phase(job: Job, phase: JobPhase, message: str = "") -> None:
    import time
    if job.status.state != phase:
        job.status.state = phase
        job.status.state_message = message
        job.status.state_last_transition = time.time()


class PendingState(State):
    def execute(self, action: BusAction) -> None:
        job = self.job
        if action == BusAction.RESTART_JOB:
            kill_job(job, JobPhase.RESTARTING,
                     retain_phases=POD_RETAIN_PHASE_NONE)
            job.status.retry_count += 1
        elif action == BusAction.ABORT_JOB:
            kill_job(job, JobPhase.ABORTING,
                     retain_phases=POD_RETAIN_PHASE_SOFT)
        elif action == BusAction.COMPLETE_JOB:
            kill_job(job, JobPhase.COMPLETING,
                     retain_phases=POD_RETAIN_PHASE_SOFT)
        elif action == BusAction.TERMINATE_JOB:
            kill_job(job, JobPhase.TERMINATING,
                     retain_phases=POD_RETAIN_PHASE_SOFT)
        else:
            sync_job(job, lambda status: JobPhase.RUNNING
                     if status.running + status.succeeded
                     >= job.spec.min_available
                     else JobPhase.PENDING)


class RunningState(State):
    def execute(self, action: BusAction) -> None:
        job = self.job
        if action == BusAction.RESTART_JOB:
            kill_job(job, JobPhase.RESTARTING,
                     retain_phases=POD_RETAIN_PHASE_NONE)
            job.status.retry_count += 1
        elif action == BusAction.ABORT_JOB:
            kill_job(job, JobPhase.ABORTING,
                     retain_phases=POD_RETAIN_PHASE_SOFT)
        elif action == BusAction.TERMINATE_JOB:
            kill_job(job, JobPhase.TERMINATING,
                     retain_phases=POD_RETAIN_PHASE_SOFT)
        elif action == BusAction.COMPLETE_JOB:
            kill_job(job, JobPhase.COMPLETING,
                     retain_phases=POD_RETAIN_PHASE_SOFT)
        else:
            total = sum(t.replicas for t in job.spec.tasks)

            def next_phase(status) -> JobPhase:
                """running.go:54-95: minSuccess early completion, then the
                all-pods-finished verdict (per-task minAvailable success
                minima, minSuccess floor, job minAvailable)."""
                if total == 0:
                    return JobPhase.RUNNING
                min_success = job.spec.min_success
                if min_success is not None \
                        and status.succeeded >= min_success:
                    return JobPhase.COMPLETED
                if status.succeeded + status.failed == total:
                    task_min_total = sum(
                        t.min_available for t in job.spec.tasks
                        if t.min_available is not None)
                    if job.spec.min_available >= task_min_total:
                        for task in job.spec.tasks:
                            if task.min_available is None:
                                continue
                            # running.go's `if taskStatus, ok := ...; ok`
                            # guard: the per-task success minimum only
                            # applies when the task has a status entry at
                            # all (e.g. a replicas=0 task never does)
                            counts = status.task_status_count.get(task.name)
                            if counts is None:
                                continue
                            if counts.get("Succeeded", 0) \
                                    < task.min_available:
                                return JobPhase.FAILED
                    if min_success is not None \
                            and status.succeeded < min_success:
                        return JobPhase.FAILED
                    if status.succeeded >= job.spec.min_available:
                        return JobPhase.COMPLETED
                    return JobPhase.FAILED
                # succeeded tasks keep counting toward the gang
                # (running.go:30-60)
                if status.running + status.succeeded < job.spec.min_available:
                    return JobPhase.PENDING
                return JobPhase.RUNNING

            sync_job(job, next_phase)


class RestartingState(State):
    def execute(self, action: BusAction) -> None:
        job = self.job
        if job.status.retry_count > job.spec.max_retry:
            _update_phase(job, JobPhase.FAILED, "number of retries exceeded")
            return

        def next_phase(status) -> JobPhase:
            if status.terminating or status.pending + status.running \
                    + status.succeeded + status.failed:
                # still draining old pods
                return JobPhase.RESTARTING
            return JobPhase.PENDING

        kill_job(job, JobPhase.RESTARTING, transition=next_phase,
                 retain_phases=POD_RETAIN_PHASE_NONE)


class AbortingState(State):
    def execute(self, action: BusAction) -> None:
        job = self.job
        if action == BusAction.RESUME_JOB:
            _update_phase(job, JobPhase.RESTARTING, "job resumed")
            job.status.retry_count += 1
            return
        kill_job(job, JobPhase.ABORTING,
                 transition=lambda status: JobPhase.ABORTED
                 if not status.terminating else JobPhase.ABORTING,
                 retain_phases=POD_RETAIN_PHASE_SOFT)


class AbortedState(State):
    def execute(self, action: BusAction) -> None:
        if action == BusAction.RESUME_JOB:
            _update_phase(self.job, JobPhase.RESTARTING, "job resumed")
            self.job.status.retry_count += 1
            return
        kill_job(self.job, JobPhase.ABORTED,
                 retain_phases=POD_RETAIN_PHASE_SOFT)


class CompletingState(State):
    def execute(self, action: BusAction) -> None:
        kill_job(self.job, JobPhase.COMPLETING,
                 transition=lambda status: JobPhase.COMPLETED
                 if not status.terminating else JobPhase.COMPLETING,
                 retain_phases=POD_RETAIN_PHASE_SOFT)


class TerminatingState(State):
    def execute(self, action: BusAction) -> None:
        kill_job(self.job, JobPhase.TERMINATING,
                 transition=lambda status: JobPhase.TERMINATED
                 if not status.terminating else JobPhase.TERMINATING,
                 retain_phases=POD_RETAIN_PHASE_SOFT)


class FinishedState(State):
    def execute(self, action: BusAction) -> None:
        # drain any pods still running when the job finished directly (a
        # minSuccess early completion leaves stragglers) — finished.go:30
        # kills with the Soft retain set; TTL deletion is the GC's job
        kill_job(self.job, self.job.status.state,
                 retain_phases=POD_RETAIN_PHASE_SOFT)


_STATES = {
    JobPhase.PENDING: PendingState,
    JobPhase.RUNNING: RunningState,
    JobPhase.RESTARTING: RestartingState,
    JobPhase.ABORTING: AbortingState,
    JobPhase.ABORTED: AbortedState,
    JobPhase.COMPLETING: CompletingState,
    JobPhase.COMPLETED: FinishedState,
    JobPhase.TERMINATING: TerminatingState,
    JobPhase.TERMINATED: FinishedState,
    JobPhase.FAILED: FinishedState,
}


def new_state(job: Job) -> State:
    return _STATES.get(job.status.state, PendingState)(job)
