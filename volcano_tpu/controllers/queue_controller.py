"""Queue controller: queue lifecycle state machine + status aggregation.

Mirrors /root/reference/pkg/controllers/queue/{queue_controller.go,
queue_controller_action.go:35-127, state/} — Open/Closed/Closing/Unknown
transitions on OpenQueue/CloseQueue commands; PodGroup counts aggregated
into Queue.Status.
"""

from __future__ import annotations

from ..api import BusAction, PodGroupPhase, QueueState
from ..apis.objects import Command, PodGroupCR, QueueCR
from ..store import ADDED, DELETED, UPDATED, ObjectStore
from .framework import Controller


class QueueController(Controller):
    NAME = "queue-controller"

    def __init__(self):
        self.store: ObjectStore = None

    def initialize(self, store: ObjectStore, **options) -> None:
        self.store = store
        store.watch("PodGroup", self._on_podgroup)
        store.watch("Command", self._on_command)

    # -- status aggregation (queue_controller_action.go syncQueue) ----------

    def _on_podgroup(self, event: str, pg: PodGroupCR, old) -> None:
        self.sync_queue(pg.spec.queue)

    def sync_queue(self, queue_name: str) -> None:
        queue: QueueCR = self.store.get("Queue", "default", queue_name)
        if queue is None:
            return
        counts = {p: 0 for p in PodGroupPhase}
        for pg in self.store.list("PodGroup"):
            if pg.spec.queue == queue_name:
                counts[pg.status.phase] = counts.get(pg.status.phase, 0) + 1
        queue.status.pending = counts.get(PodGroupPhase.PENDING, 0)
        queue.status.running = counts.get(PodGroupPhase.RUNNING, 0)
        queue.status.unknown = counts.get(PodGroupPhase.UNKNOWN, 0)
        queue.status.inqueue = counts.get(PodGroupPhase.INQUEUE, 0)
        self.store.update_status(queue)

    # -- open/close state machine (queue/state/*.go) -------------------------

    def _on_command(self, event: str, cmd: Command, old) -> None:
        if event != ADDED:
            return
        target = cmd.target_object or {}
        if target.get("kind") != "Queue":
            return
        queue: QueueCR = self.store.get("Queue", "default", target.get("name"))
        self.store.delete("Command", cmd.metadata.namespace, cmd.metadata.name)
        if queue is None:
            return
        if cmd.action == BusAction.OPEN_QUEUE:
            queue.status.state = QueueState.OPEN
        elif cmd.action == BusAction.CLOSE_QUEUE:
            active = any(pg.spec.queue == queue.metadata.name
                         and pg.status.phase in (PodGroupPhase.RUNNING,
                                                 PodGroupPhase.INQUEUE)
                         for pg in self.store.list("PodGroup"))
            queue.status.state = (QueueState.CLOSING if active
                                  else QueueState.CLOSED)
        self.store.update_status(queue)
        self.sync_queue(queue.metadata.name)
