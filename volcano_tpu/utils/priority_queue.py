"""Priority queue on a less-fn, mirroring
/root/reference/pkg/scheduler/util/priority_queue.go.

The queue is stable for equal-priority items only up to heap order, exactly
like the reference (container/heap); callers that need determinism must make
their less-fn total (the session order fns fall back to creation time + uid).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, List, Optional


class PriorityQueue:
    def __init__(self, less_fn: Callable[[Any, Any], bool]):
        self._less = less_fn
        self._heap: List["_Item"] = []
        self._counter = itertools.count()

    def push(self, it: Any) -> None:
        heapq.heappush(self._heap, _Item(it, self._less, next(self._counter)))

    def clone(self) -> "PriorityQueue":
        """Faithful copy INCLUDING insertion-sequence tie-breaks: popping
        the clone yields exactly the order the original would (re-pushing
        values would assign fresh sequences and reorder equal-key items).
        Used by the strict engine's pop-prediction simulation."""
        out = PriorityQueue(self._less)
        out._heap = list(self._heap)          # _Item is never mutated
        next_seq = max((it._seq for it in self._heap), default=-1) + 1
        out._counter = itertools.count(next_seq)
        return out

    def pop(self) -> Any:
        if not self._heap:
            return None
        return heapq.heappop(self._heap).value

    def peek(self) -> Optional[Any]:
        return self._heap[0].value if self._heap else None

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)


class _Item:
    __slots__ = ("value", "_less", "_seq")

    def __init__(self, value, less_fn, seq):
        self.value = value
        self._less = less_fn
        self._seq = seq

    def __lt__(self, other: "_Item") -> bool:
        if self._less(self.value, other.value):
            return True
        if self._less(other.value, self.value):
            return False
        return self._seq < other._seq
