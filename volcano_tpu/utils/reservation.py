"""Global reservation singleton (mirrors the util.Reservation state in
/root/reference/pkg/scheduler/util/scheduler_helper.go:254-266), shared by
the elect/reserve actions, the reservation plugin, and allocate's
locked-node exclusion."""

from __future__ import annotations

from typing import Dict, Optional


class ResourceReservation:
    def __init__(self):
        self.target_job = None
        self.locked_nodes: Dict[str, object] = {}

    def reset(self) -> None:
        self.target_job = None
        self.locked_nodes.clear()


Reservation = ResourceReservation()
