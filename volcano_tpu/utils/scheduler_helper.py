"""Hot-loop helpers for the callback (CPU) allocate path.

Mirrors /root/reference/pkg/scheduler/util/scheduler_helper.go:36-266 —
PredicateNodes with adaptive feasible-node sampling, PrioritizeNodes score
merge, SelectBestNode. The reference parallelizes these over 16 goroutines;
the TPU engines replace them entirely (ops/place.py), so the callback path
here is a straightforward loop kept as the semantic baseline.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from ..api import FitErrors, NodeInfo, TaskInfo

# options.go:38-41 defaults
DEFAULT_MIN_NODES_TO_FIND = 100
DEFAULT_MIN_PERCENTAGE_OF_NODES_TO_FIND = 5
DEFAULT_PERCENTAGE_OF_NODES_TO_FIND = 100


def calculate_num_feasible_nodes(num_all_nodes: int,
                                 percentage: int = DEFAULT_PERCENTAGE_OF_NODES_TO_FIND,
                                 min_nodes: int = DEFAULT_MIN_NODES_TO_FIND,
                                 min_percent: int = DEFAULT_MIN_PERCENTAGE_OF_NODES_TO_FIND,
                                 ) -> int:
    """CalculateNumOfFeasibleNodesToFind (scheduler_helper.go:49-68)."""
    if num_all_nodes <= min_nodes or percentage >= 100:
        return num_all_nodes
    adaptive = percentage
    if adaptive == 0:
        adaptive = int(50 - num_all_nodes / 125)
        if adaptive < min_percent:
            adaptive = min_percent
    num = num_all_nodes * adaptive // 100
    return max(num, min_nodes)


def predicate_nodes(task: TaskInfo, nodes: List[NodeInfo],
                    fn: Callable[[TaskInfo, NodeInfo], None],
                    percentage: int = DEFAULT_PERCENTAGE_OF_NODES_TO_FIND,
                    ) -> Tuple[List[NodeInfo], FitErrors]:
    """PredicateNodes (scheduler_helper.go:71-127): first K feasible nodes."""
    to_find = calculate_num_feasible_nodes(len(nodes), percentage)
    feasible: List[NodeInfo] = []
    errors = FitErrors()
    for node in nodes:
        if len(feasible) >= to_find:
            break
        try:
            fn(task, node)
        except Exception as err:
            errors.set_node_error(node.name, getattr(err, "fit_error", err))
            continue
        feasible.append(node)
    return feasible, errors


def prioritize_nodes(task: TaskInfo, nodes: List[NodeInfo],
                     batch_fn, map_fn) -> Dict[float, List[NodeInfo]]:
    """PrioritizeNodes (scheduler_helper.go:130-192): per-node map scores +
    batch scores summed, grouped score -> nodes."""
    scores: Dict[str, float] = {n.name: 0.0 for n in nodes}
    for node in nodes:
        scores[node.name] += map_fn(task, node)
    for name, s in (batch_fn(task, nodes) or {}).items():
        if name in scores:
            scores[name] += s
    grouped: Dict[float, List[NodeInfo]] = {}
    for node in nodes:
        grouped.setdefault(scores[node.name], []).append(node)
    return grouped


def select_best_node(node_scores: Dict[float, List[NodeInfo]],
                     deterministic: bool = True,
                     rng: Optional[random.Random] = None
                     ) -> Optional[NodeInfo]:
    """SelectBestNode (scheduler_helper.go:210-225). The reference picks a
    random node among the max-score group; we default to the first (lowest
    index) for reproducibility. The random behavior requires the caller to
    pass its own seeded ``rng`` (vlint VT003) — without one the pick stays
    deterministic rather than drawing from the hidden global RNG."""
    if not node_scores:
        return None
    best = node_scores[max(node_scores)]
    if not best:
        return None
    if deterministic or rng is None:
        return best[0]
    return rng.choice(best)
