from .priority_queue import PriorityQueue

__all__ = ["PriorityQueue"]
