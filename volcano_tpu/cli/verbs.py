"""Standalone verb binaries — vsub/vcancel/vsuspend/vresume/vjobs/vqueues.

The reference builds one binary per verb around the same pkg/cli
(Makefile:172-180 `command-lines`); here each is a console_scripts entry
point (pyproject.toml) wrapping vcctl's parser with the verb pre-applied.

Standalone invocations need a cluster to talk to; the in-process CLI talks
to a store, so each verb accepts --rpc host:port to reach a running
snapshot-RPC sidecar deployment, or operates on a fresh in-process system
for dry runs (the vcctl main prints a clear error when no store is
attached).
"""

from __future__ import annotations

import sys
from typing import List, Optional

from .vcctl import main


def _run(prefix: List[str], argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    return main(prefix + list(argv))


def vsub(argv=None) -> int:
    """vsub == vcctl job run."""
    return _run(["job", "run"], argv)


def vcancel(argv=None) -> int:
    return _run(["job", "delete"], argv)


def vsuspend(argv=None) -> int:
    return _run(["job", "suspend"], argv)


def vresume(argv=None) -> int:
    return _run(["job", "resume"], argv)


def vscale(argv=None) -> int:
    """vscale == vcctl job scale: rewrite an elastic gang's desired
    member count through the scheduler's journaled Command funnel
    (docs/design/elastic-gangs.md). In-process callers pass the running
    scheduler's funnel via vcctl.main(..., funnel=...)."""
    return _run(["job", "scale"], argv)


def vjobs(argv=None) -> int:
    return _run(["job", "list"], argv)


def vqueues(argv=None) -> int:
    return _run(["queue", "list"], argv)


def redrive_dead_letter(argv=None) -> int:
    """vredrive == vcctl cache redrive-dead-letter: re-queue every
    dead-lettered side effect with a fresh retry budget once the
    underlying fault (bad node, apiserver outage) is fixed
    (docs/robustness.md). In-process callers pass the running
    scheduler's cache via vcctl.main(..., cache=...)."""
    return _run(["cache", "redrive-dead-letter"], argv)
