from .vcctl import main, JobCommands, QueueCommands

__all__ = ["main", "JobCommands", "QueueCommands"]
