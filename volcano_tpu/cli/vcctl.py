"""vcctl: the operator CLI.

Mirrors /root/reference/{cmd/cli/vcctl.go:47-49, pkg/cli/job/*, pkg/cli/queue/*}:
``job {run,list,view,suspend,resume,scale,delete}``, ``queue {create,get,
list,operate,delete}``, ``version``. Job suspend/resume/delete post bus
Command CRs owner-referenced to the Job (pkg/cli/job/util.go:69-95),
exactly like the reference — the job controller consumes them
asynchronously.

With the running scheduler's elastic Command funnel attached
(``main(..., funnel=...)``, like the in-process cache/trace verbs),
``job suspend|resume|scale`` route through the journaled+fenced funnel
instead (docs/design/elastic-gangs.md): the verb enqueues durably and
applies at the next cycle boundary. ``job scale`` exists ONLY on that
path — rewriting the desired-members annotation anywhere but the funnel
is a vlint VT020 violation, so there is no store fallback for it.

The standalone verb entry points (vsub/vcancel/vsuspend/vresume/vjobs/
vqueues, Makefile:172-180) are exposed as functions of the same commands.
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from .. import __version__
from ..api import BusAction, QueueState, Resource
from ..apis.objects import (Command, Job, JobSpec, ObjectMeta, PodTemplate,
                            QueueCR, QueueSpecCR, TaskSpec)
from ..store import ObjectStore


class JobCommands:
    """pkg/cli/job analogue."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def run(self, name: str, namespace: str = "default", queue: str = "default",
            replicas: int = 1, min_available: Optional[int] = None,
            requests: Optional[dict] = None, image: str = "busybox",
            scheduler: str = "volcano",
            min_success: Optional[int] = None) -> Job:
        """constructLaunchJobFlagsJob (pkg/cli/job/run.go:71-165)."""
        res = Resource.from_dict(requests or {"cpu": "1", "memory": "1Gi"})
        job = Job(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=JobSpec(
                queue=queue, scheduler_name=scheduler,
                min_available=min_available or replicas,
                min_success=min_success,
                tasks=[TaskSpec(name="default", replicas=replicas,
                                template=PodTemplate(
                                    resources=res,
                                    containers=[{"name": name,
                                                 "image": image}]))]))
        return self.store.create(job)

    def list(self, namespace: Optional[str] = None) -> List[Job]:
        return self.store.list("Job", namespace)

    def view(self, name: str, namespace: str = "default") -> Optional[Job]:
        return self.store.get("Job", namespace, name)

    def _command(self, name: str, namespace: str, action: BusAction) -> None:
        """createJobCommand (pkg/cli/job/util.go:69-95)."""
        self.store.create(Command(
            metadata=ObjectMeta(
                name=f"{name}-{action.value.lower()}-{ObjectMeta().uid}",
                namespace=namespace,
                owner_references=[{"kind": "Job", "name": name}]),
            action=action,
            target_object={"kind": "Job", "name": name}))

    def suspend(self, name: str, namespace: str = "default") -> None:
        self._command(name, namespace, BusAction.ABORT_JOB)

    def resume(self, name: str, namespace: str = "default") -> None:
        self._command(name, namespace, BusAction.RESUME_JOB)

    def delete(self, name: str, namespace: str = "default") -> None:
        self.store.delete("Job", namespace, name)


class QueueCommands:
    """pkg/cli/queue analogue."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def create(self, name: str, weight: int = 1,
               capability: Optional[dict] = None,
               reclaimable: bool = True, hierarchy: str = "",
               hierarchy_weights: str = "") -> QueueCR:
        cap = Resource.from_dict(capability) if capability else None
        annotations = {}
        if hierarchy:
            annotations["volcano.sh/hierarchy"] = hierarchy
        if hierarchy_weights:
            annotations["volcano.sh/hierarchy-weights"] = hierarchy_weights
        return self.store.create(QueueCR(
            metadata=ObjectMeta(name=name, namespace="default",
                                annotations=annotations),
            spec=QueueSpecCR(weight=weight, capability=cap,
                             reclaimable=reclaimable)))

    def get(self, name: str) -> Optional[QueueCR]:
        return self.store.get("Queue", "default", name)

    def list(self) -> List[QueueCR]:
        return self.store.list("Queue")

    def operate(self, name: str, action: str) -> None:
        bus = {"open": BusAction.OPEN_QUEUE,
               "close": BusAction.CLOSE_QUEUE}[action]
        self.store.create(Command(
            metadata=ObjectMeta(name=f"{name}-{action}-{ObjectMeta().uid}",
                                namespace="default"),
            action=bus, target_object={"kind": "Queue", "name": name}))

    def delete(self, name: str) -> None:
        self.store.delete("Queue", "default", name)


def _fmt_job(job: Job) -> str:
    return (f"{job.metadata.namespace}/{job.metadata.name}\t"
            f"queue={job.spec.queue}\tstate={job.status.state.value}\t"
            f"running={job.status.running}\tsucceeded={job.status.succeeded}")


def _fmt_queue(q: QueueCR) -> str:
    return (f"{q.metadata.name}\tweight={q.spec.weight}\t"
            f"state={q.status.state.value}\tinqueue={q.status.inqueue}\t"
            f"running={q.status.running}")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="vcctl")
    sub = parser.add_subparsers(dest="group")

    job = sub.add_parser("job").add_subparsers(dest="verb")
    run = job.add_parser("run")
    run.add_argument("--name", required=True)
    run.add_argument("--namespace", default="default")
    run.add_argument("--queue", default="default")
    run.add_argument("--replicas", type=int, default=1)
    run.add_argument("--min", type=int, default=None)
    run.add_argument("--min-success", type=int, default=None,
                     dest="min_success")
    run.add_argument("--requests", default="cpu=1,memory=1Gi")
    run.add_argument("--image", default="busybox")
    for verb in ("list", "view", "suspend", "resume", "delete"):
        p = job.add_parser(verb)
        if verb != "list":
            p.add_argument("--name", required=True)
        p.add_argument("--namespace", default="default")
    js = job.add_parser(
        "scale", description="Rewrite an elastic gang's desired member "
                             "count through the scheduler's journaled "
                             "Command funnel; grow-shrink converges the "
                             "gang over the next cycles "
                             "(docs/design/elastic-gangs.md)")
    js.add_argument("--name", required=True)
    js.add_argument("--namespace", default="default")
    js.add_argument("--desired", type=int, required=True,
                    help="target member count (min_available still floors "
                         "the gang; 0 parks it at min)")
    jt = job.add_parser(
        "timeline", description="The job's retained lifecycle timeline "
                                "(docs/observability.md): every causal "
                                "event — arrival, solve verdicts, bind "
                                "intents, acks, queue moves, elastic "
                                "grow/shrink, completion — stamped with "
                                "its originating cycle/partition/epoch; "
                                "process-local like the trace verbs")
    jt.add_argument("--name", required=True)

    queue = sub.add_parser("queue").add_subparsers(dest="verb")
    qc = queue.add_parser("create")
    qc.add_argument("--name", required=True)
    qc.add_argument("--weight", type=int, default=1)
    qc.add_argument("--hierarchy", default="")
    qc.add_argument("--hierarchy-weights", default="",
                    dest="hierarchy_weights")
    for verb in ("get", "delete"):
        queue.add_parser(verb).add_argument("--name", required=True)
    queue.add_parser("list")
    qo = queue.add_parser("operate")
    qo.add_argument("--name", required=True)
    qo.add_argument("--action", choices=["open", "close"], required=True)

    cache = sub.add_parser("cache").add_subparsers(dest="verb")
    cache.add_parser(
        "redrive-dead-letter",
        description="Re-queue every dead-lettered side effect with a "
                    "fresh retry budget (after the underlying fault is "
                    "fixed) — SchedulerCache.redrive_dead_letter")
    cache.add_parser("dead-letter",
                     description="List the dead-lettered side effects")
    cache.add_parser(
        "inflight",
        description="List the in-flight ledger: executor-accepted "
                    "bind/evicts still awaiting their cluster ack, with "
                    "age and deadline, plus the watchdog's resolution "
                    "totals (docs/robustness.md feedback failure model)")

    trace = sub.add_parser(
        "trace", description="Flight-recorder verbs "
                             "(docs/observability.md); in-process like the "
                             "cache verbs — they read the running "
                             "scheduler's obs.TRACE/obs.AUDIT").add_subparsers(
        dest="verb")
    td = trace.add_parser(
        "dump", description="Write the recorded cycle ring as Chrome "
                            "trace-event JSON (perfetto-loadable)")
    td.add_argument("--out", help="file to write (default: stdout)")
    tw = trace.add_parser(
        "why", description="The last audited decision for a job: "
                           "admitted/denied/pipelined/preempted + reason")
    tw.add_argument("--job", required=True)

    leader = sub.add_parser(
        "leader", description="HA control-plane verbs "
                              "(docs/robustness.md): inspect the "
                              "scheduler lease / fencing epoch in the "
                              "store").add_subparsers(dest="verb")
    ls = leader.add_parser(
        "status", description="Who holds the scheduler lease, its "
                              "fencing epoch, and how stale the renew "
                              "timestamp is")
    ls.add_argument("--name", default="vc-scheduler")
    ls.add_argument("--namespace", default="volcano-system")

    fed = sub.add_parser(
        "federation", description="Federated control-plane verbs "
                                  "(docs/federation.md): inspect the "
                                  "per-partition scheduler leases in "
                                  "the store").add_subparsers(dest="verb")
    fs = fed.add_parser(
        "status", description="Per-partition leadership: who holds each "
                              "partition's lease, its fencing epoch, and "
                              "renew staleness")
    fs.add_argument("--name", default="vc-scheduler",
                    help="base lease name (partitions are <name>-p<i>)")
    fs.add_argument("--namespace", default="volcano-system")
    fs.add_argument("--partitions", type=int, default=0,
                    help="probe exactly N partitions; 0 discovers "
                         "contiguously from p0 until the first missing "
                         "lease")
    fed.add_parser(
        "rebalance-status",
        description="The load-driven rebalancer's per-partition state "
                    "(docs/federation.md): executed moves, abstentions, "
                    "flap-blocked queues and thresholds — read from the "
                    "process-local metrics detail, like the flight-"
                    "recorder verbs")
    fed.add_parser(
        "elastic-status",
        description="Elastic membership per-partition state "
                    "(docs/federation.md): live partition count, "
                    "split/merge totals, hot/idle streaks, flap-guard "
                    "windows and the last split/merge records — read "
                    "from the process-local metrics detail")

    dev = sub.add_parser(
        "device", description="Accelerator-mesh verbs (docs/robustness.md "
                              "mesh failure model): the per-device health "
                              "lattice, quarantine windows and the "
                              "degradation rung — read from the process-"
                              "local metrics detail").add_subparsers(
                                  dest="verb")
    dev.add_parser(
        "status", description="Fleet window plus every known device's "
                              "lattice state (ok/suspect/quarantined/"
                              "probe), consecutive faults, window "
                              "remaining and readmission count")

    st = sub.add_parser(
        "store", description="Store-boundary verbs (docs/robustness.md "
                             "store failure model): object counts, "
                             "fault/retry funnel totals, watch stream "
                             "staleness").add_subparsers(dest="verb")
    st.add_parser(
        "status", description="Current resourceVersion, per-kind object "
                              "counts, volcano_store_faults/retries "
                              "totals and per-stream watch state")

    slo = sub.add_parser(
        "slo", description="SLO verbs (docs/observability.md): the "
                           "declarative objectives evaluated over the "
                           "lifecycle timeline store — process-local "
                           "like the trace verbs").add_subparsers(
        dest="verb")
    slo.add_parser(
        "status", description="Evaluate every configured objective at "
                              "the store's current virtual time: "
                              "compliance, sample count and per-window "
                              "burn rates")

    sub.add_parser("version")
    return parser


def parse_requests(text: str) -> dict:
    out = {}
    for part in text.split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def main(argv: Optional[List[str]] = None, store: Optional[ObjectStore] = None,
         out=print, cache=None, funnel=None) -> int:
    args = build_parser().parse_args(argv)
    if args.group == "version":
        out(f"vcctl version {__version__}")
        return 0
    if args.group == "trace":
        # flight-recorder verbs (docs/observability.md): read the
        # process-local recorder — in-process callers share the running
        # scheduler's obs globals, same deployment model as the cache verbs
        from ..obs import AUDIT, TRACE
        if args.verb == "dump":
            if args.out:
                TRACE.dump(args.out)
                out(f"wrote {TRACE.cycles_recorded()} recorded cycle(s) "
                    f"to {args.out}")
            else:
                out(TRACE.dump())
            return 0
        if args.verb == "why":
            # timeline-backed (obs/lifecycle.py): the audit verdict
            # extended with the causal history the ring ages out of
            from ..obs.lifecycle import why as timeline_why
            rec = timeline_why(args.job)
            if rec is None:
                out(f"no decision recorded for job {args.job!r} in the "
                    f"last {AUDIT.cycles_retained()} retained cycle(s)")
                return 1
            import json
            out(json.dumps(rec, sort_keys=True))
            return 0
        build_parser().print_help()
        return 1
    if args.group == "job" and args.verb == "timeline":
        # process-local, like the trace verbs: read the running
        # scheduler's lifecycle timeline store (docs/observability.md)
        import json
        from ..obs import TIMELINE
        tl = TIMELINE.timeline(args.name)
        if tl is None:
            out(f"no timeline retained for job {args.name!r} "
                f"({TIMELINE.job_count()} job(s) retained)")
            return 1
        out(f"job {tl['job']}: {len(tl['events'])} event(s)")
        for ev in tl["events"]:
            extras = {k: v for k, v in ev.items()
                      if k not in ("ev", "cycle", "part", "epoch",
                                   "eid", "t")}
            tail = " " + json.dumps(extras, sort_keys=True) if extras \
                else ""
            out(f"t={ev['t']}\tcycle={ev['cycle']}\t"
                f"p{ev['part']}/e{ev['epoch']}\t{ev['ev']}{tail}")
        return 0
    if args.group == "slo":
        if args.verb == "status":
            from ..obs import SLO_ENGINE, TIMELINE
            status = SLO_ENGINE.publish(now=TIMELINE.now())
            if not status:
                out("no SLO objectives configured")
                return 1
            for obj in status:
                burns = " ".join(
                    f"burn[{w}]={r}" for w, r in sorted(
                        obj["burn_rate"].items(),
                        key=lambda kv: float(kv[0])))
                out(f"{obj['slo']}\tmetric={obj['metric']}\t"
                    f"ok={obj['ok']}\tcompliance={obj['compliance']}\t"
                    f"samples={obj['samples']}\t"
                    f"threshold_s={obj['threshold_s']}\t{burns}")
            return 0
        build_parser().print_help()
        return 1
    if args.group == "cache":
        # operator verbs against the scheduler cache (dead-letter ops,
        # docs/robustness.md) — in-process callers pass the live
        # SchedulerCache (VolcanoSystem.cache)
        if cache is None:
            out("no scheduler cache attached (in-process CLI requires "
                "the running scheduler's cache)")
            return 1
        if args.verb == "redrive-dead-letter":
            moved = cache.redrive_dead_letter()
            out(f"redrove {moved} dead-lettered side effects")
        elif args.verb == "dead-letter":
            for key, (op, task) in sorted(cache.dead_letter.items()):
                out(f"{key}\top={op}\ttask={task.uid}\t"
                    f"node={task.node_name or '-'}")
            out(f"{len(cache.dead_letter)} dead-lettered")
        elif args.verb == "inflight":
            ledger = getattr(cache, "inflight", None)
            if ledger is None:
                out("no in-flight ledger attached")
                return 1
            now = ledger.time_fn()
            for e in sorted(ledger.entries(),
                            key=lambda e: (e.registered_at, e.uid)):
                out(f"{e.op}/{e.uid}\tnode={e.node or '-'}\t"
                    f"age={now - e.registered_at:.1f}s\t"
                    f"deadline_in={e.deadline - now:.1f}s")
            detail = ledger.detail(now)
            res = " ".join(f"{k}={v}" for k, v in
                           detail["resolved"].items())
            out(f"{detail['open']} in flight; "
                f"oldest {detail['oldest_age_s']:.1f}s; "
                f"resolved: {res or '-'}")
        return 0
    if args.group == "federation" and args.verb == "rebalance-status":
        # process-local (metrics detail), like the trace verbs — the
        # rebalancer lives in the scheduler process, not the store
        import json
        from .. import metrics
        detail = metrics.health_detail().get("overload", {}) \
            .get("rebalance", {})
        if not detail:
            out("no rebalancer state recorded — the load-driven "
                "rebalancer is not enabled (or this process runs no "
                "partition leader)")
            return 1
        for pid in sorted(detail, key=int):
            d = detail[pid]
            out(f"p{pid}\tmoves={d.get('moves', 0)}\t"
                f"abstentions={d.get('abstentions', 0)}\t"
                f"refused={d.get('refused', 0)}\t"
                f"blocked={sorted(d.get('blocked_queues', {}))}")
            if d.get("last_move"):
                out(f"p{pid}\tlast_move={json.dumps(d['last_move'], sort_keys=True)}")
        return 0
    if args.group == "federation" and args.verb == "elastic-status":
        # process-local, like rebalance-status: the elastic controller
        # lives in each partition leader's scheduler process
        import json
        from .. import metrics
        health = metrics.health_detail()
        detail = health.get("federation", {}).get("elastic", {})
        if not detail:
            out("no elastic state recorded — elastic membership is not "
                "enabled (or this process runs no partition leader)")
            return 1
        out(f"partitions={health.get('partition_count', 0)}\t"
            f"splits={health.get('partition_splits_total', {})}\t"
            f"merges={health.get('partition_merges_total', {})}")
        for pid in sorted(detail, key=int):
            d = detail[pid]
            out(f"p{pid}\tretiring={d.get('retiring', False)}\t"
                f"splits={d.get('splits', 0)}\t"
                f"merges={d.get('merges', 0)}\t"
                f"abstentions={d.get('abstentions', 0)}\t"
                f"refused={d.get('refused', 0)}\t"
                f"hot={d.get('hot_streak', 0)}\t"
                f"idle={d.get('idle_streak', 0)}\t"
                f"block_until={d.get('block_until', 0)}")
            for k in ("last_split", "last_merge"):
                if d.get(k):
                    out(f"p{pid}\t{k}={json.dumps(d[k], sort_keys=True)}")
        return 0
    if args.group == "device" and args.verb == "status":
        # process-local, like rebalance-status: the health lattice lives
        # in the scheduler process that runs the sharded solver
        from .. import metrics
        detail = metrics.health_detail().get("device", {})
        counts = metrics.mesh_counts()
        out(f"fleet\tavailable={detail.get('available', True)}\t"
            f"consecutive_faults={detail.get('consecutive_faults', 0)}\t"
            f"total_faults={detail.get('total_faults', 0)}\t"
            f"last_kind={detail.get('last_kind')}\t"
            f"cooldown_remaining_s={detail.get('cooldown_remaining_s', 0.0)}")
        heals = {k.split("/", 1)[1]: int(v)
                 for k, v in sorted(counts.items())
                 if k.startswith("heals/")}
        quarantines = {k.split("/", 1)[1]: int(v)
                       for k, v in sorted(counts.items())
                       if k.startswith("quarantines/")}
        out(f"mesh\trung={int(counts.get('rung', 0))}\t"
            f"devices_healthy={int(counts.get('devices_healthy', 0))}\t"
            f"readmissions={int(counts.get('readmissions', 0))}\t"
            f"heals={heals}\tquarantines={quarantines}")
        devices = detail.get("devices", {})
        if not devices:
            out("no per-device state recorded — the sharded engine has "
                "not run in this process (or the lattice was reset)")
            return 0
        for did in sorted(devices, key=int):
            d = devices[did]
            out(f"device/{did}\tstate={d.get('state')}\t"
                f"consecutive_faults={d.get('consecutive_faults', 0)}\t"
                f"total_faults={d.get('total_faults', 0)}\t"
                f"last_kind={d.get('last_kind')}\t"
                f"window_remaining_s={d.get('window_remaining_s', 0.0)}\t"
                f"readmissions={d.get('readmissions', 0)}")
        return 0
    if args.group == "job" and args.verb in ("suspend", "resume", "scale"):
        if funnel is not None:
            # the scheduler's elastic lifecycle path: submit journals the
            # verb (epoch-stamped), consume applies it at the next cycle
            # boundary — never a direct annotation write from here (VT020)
            uid = funnel.resolve_job(args.name, args.namespace)
            if uid is None:
                out(f"job {args.namespace}/{args.name} not known to the "
                    f"scheduler cache")
                return 1
            ok = funnel.submit(args.verb, uid,
                               getattr(args, "desired", None))
            if not ok:
                out(f"{args.verb} {args.namespace}/{args.name} rejected: "
                    f"stale fencing epoch")
                return 1
            out(f"{args.verb} {args.namespace}/{args.name} queued "
                f"(applies at the next cycle boundary)")
            return 0
        if args.verb == "scale":
            # no store fallback by design: a desired-members rewrite
            # outside the journaled funnel is exactly what VT020 forbids
            out("job scale requires the running scheduler's command "
                "funnel (in-process CLI: main(..., funnel=...))")
            return 1
    if store is None:
        out("no cluster store attached (in-process CLI requires a store)")
        return 1
    if args.group == "store":
        if args.verb == "status":
            from .. import metrics
            if hasattr(store, "current_rv"):
                out(f"resourceVersion={store.current_rv()}")
            for kind in getattr(store, "KINDS", ()):
                n = len(store.list(kind))
                if n:
                    out(f"{kind}\t{n}")
            counts = metrics.store_counts()
            for family in ("faults", "retries", "watch_resumes"):
                for key, v in sorted(counts[family].items()):
                    out(f"{family}/{key}\t{int(v)}")
            detail = metrics.health_detail().get("store", {})
            for stream in detail.get("streams", []):
                out(f"watch/{stream['kind']}\tlast_rv={stream['last_rv']}"
                    f"\ttorn={stream['torn']}"
                    f"\tresumes={stream['resumes']}"
                    f"\trelists={stream['relists']}")
            if "staleness" in detail:
                out(f"watch_staleness={detail['staleness']}")
            return 0
        build_parser().print_help()
        return 1
    if args.group == "federation":
        if args.verb == "status":
            import time as _time
            from ..leaderelection import partition_lease_name
            probe = args.partitions if args.partitions > 0 else 64
            found = 0
            for pid in range(probe):
                lease = store.get("Lease", args.namespace,
                                  partition_lease_name(args.name, pid))
                if lease is None:
                    if args.partitions > 0:
                        out(f"p{pid}\tholder=-\tno lease (partition idle "
                            f"or not federated)")
                        continue
                    break
                found += 1
                age = _time.time() - lease.renew_time if lease.renew_time \
                    else float("inf")
                live = age <= lease.lease_duration
                out(f"p{pid}\tholder={lease.holder or '-'}\t"
                    f"epoch={int(getattr(lease, 'epoch', 0))}\t"
                    f"renew_age_s={age:.1f}\t"
                    f"{'LIVE' if live else 'EXPIRED'}")
            if not found and args.partitions <= 0:
                out(f"no partition leases under {args.namespace}/"
                    f"{args.name}-p* — federation not enabled")
                return 1
            return 0
        build_parser().print_help()
        return 1
    if args.group == "leader":
        if args.verb == "status":
            import time as _time
            lease = store.get("Lease", args.namespace, args.name)
            if lease is None:
                out(f"no lease {args.namespace}/{args.name} — no leader "
                    f"elected (or HA not enabled)")
                return 1
            age = _time.time() - lease.renew_time if lease.renew_time \
                else float("inf")
            live = age <= lease.lease_duration
            out(f"holder={lease.holder or '-'}\t"
                f"epoch={int(getattr(lease, 'epoch', 0))}\t"
                f"renew_age_s={age:.1f}\t"
                f"lease_duration_s={lease.lease_duration}\t"
                f"{'LIVE' if live else 'EXPIRED'}")
            return 0
        build_parser().print_help()
        return 1
    if args.group == "job":
        jc = JobCommands(store)
        if args.verb == "run":
            jc.run(args.name, args.namespace, args.queue, args.replicas,
                   args.min, parse_requests(args.requests), args.image,
                   min_success=args.min_success)
        elif args.verb == "list":
            for j in jc.list(args.namespace):
                out(_fmt_job(j))
        elif args.verb == "view":
            j = jc.view(args.name, args.namespace)
            out(_fmt_job(j) if j else f"job {args.name} not found")
        elif args.verb == "suspend":
            jc.suspend(args.name, args.namespace)
        elif args.verb == "resume":
            jc.resume(args.name, args.namespace)
        elif args.verb == "delete":
            jc.delete(args.name, args.namespace)
        return 0
    if args.group == "queue":
        qc = QueueCommands(store)
        if args.verb == "create":
            qc.create(args.name, args.weight,
                      hierarchy=args.hierarchy,
                      hierarchy_weights=args.hierarchy_weights)
        elif args.verb == "get":
            q = qc.get(args.name)
            out(_fmt_queue(q) if q else f"queue {args.name} not found")
        elif args.verb == "list":
            for q in qc.list():
                out(_fmt_queue(q))
        elif args.verb == "operate":
            qc.operate(args.name, args.action)
        elif args.verb == "delete":
            qc.delete(args.name)
        return 0
    build_parser().print_help()
    return 1
