"""Device-fault containment: classify accelerator errors, quarantine the
failing chip, and cool the fleet down only when nothing smaller works.

A batched solve can fail for two very different reasons, and the right
response differs (docs/robustness.md):

- **Solver logic faults** (non-finite scores, garbage indices, shape
  bugs — ``actions.allocate.SolverFault``): the device is fine, the
  program is wrong. Falling back to the sequential placer and retrying
  the device engine next cycle is correct.
- **Device faults** (XLA ``RESOURCE_EXHAUSTED`` OOM, device-lost,
  backend-internal errors): retrying the device engine immediately just
  re-fails — and after a device loss the device-resident tensor mirrors
  are gone, so any cached device state is poison.

``classify_device_fault`` tells the two apart. Containment is now a
PER-DEVICE lattice plus the original fleet-level cool-down:

- **Attributed faults** (the error exposes the failing shard — an
  injected ``DeviceFaultError.device`` or a device ordinal in the XLA
  message, ``attribute_device_fault``) quarantine ONLY that device::

      OK --attributed fault--> QUARANTINED (excluded from the mesh;
                    per-device window, doubling on repeat)
      QUARANTINED --window expires--> PROBE (still excluded from LIVE
                    solves; allocate runs a throwaway dry-run solve on
                    the device — never a live decision)
      PROBE --dry-run succeeds--> readmitted (OK; the mesh re-forms
                    over the grown device set, epoch bumped)
      PROBE --dry-run faults--> QUARANTINED, window doubled (capped)

  The degradation ladder rides the healthy set: full mesh → re-formed
  mesh over the survivors (byte-identical decisions — the unified
  solver is mesh-size invariant by construction) → single device → the
  CPU placer, each rung only when the one above is unavailable.

- **Unattributed faults** (the error names no shard) mark every known
  device SUSPECT and open the original FLEET window — the D=1
  degenerate case, and exactly the pre-lattice behavior::

      OK --fault--> COOLDOWN (allocate degrades to the CPU/callbacks
                    engine; volcano_device_healthy=0)
      COOLDOWN --window expires--> PROBE (the next cycle attempts the
                    device engine once)
      PROBE --success--> OK (counters reset; SUSPECT marks clear)
      PROBE --fault--> COOLDOWN, window doubled (capped)

  SUSPECT is a marker, not an exclusion: suspicion without attribution
  must not shrink the mesh (it would shrink it to nothing), so suspect
  devices stay in the healthy set and the fleet window is what gates
  dispatch.

Every transition is exported (``volcano_device_faults_total{kind}``,
``volcano_device_quarantines_total{kind}``,
``volcano_mesh_devices_healthy``, ``volcano_device_healthy``,
/healthz?detail). The windows run on an injectable ``time_fn`` so the
sim and tests drive them on virtual time; ``reset`` (sim restarts)
clears the per-device lattice too — health lives in process memory.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, Iterable, List, Optional

DEFAULT_COOLDOWN_S = 30.0
DEFAULT_MAX_COOLDOWN_S = 480.0

# substrings that mark an XLA runtime error as a DEVICE fault rather
# than a program bug (jaxlib surfaces both through XlaRuntimeError)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "OOM")
_LOST_MARKERS = ("DEVICE_LOST", "device lost", "Device lost",
                 "DATA_LOSS", "failed to enqueue")
# a straggling shard surfaces as a collective timeout — a device fault
# (the chip is unhealthy), not a program bug
_SLOW_MARKERS = ("DEADLINE_EXCEEDED", "collective timed out")

# message shapes that expose WHICH device faulted — jaxlib's device-lost
# and per-core OOM errors name the ordinal in these forms
_DEVICE_ID_PATTERNS = (
    re.compile(r"\bdevice[:= ]+(\d+)\b", re.IGNORECASE),
    re.compile(r"\bTPU[_ ](\d+)\b"),
    re.compile(r"\bshard[:= ]+(\d+)\b", re.IGNORECASE),
)


class DeviceFaultError(RuntimeError):
    """A simulated device error (chaos.DeviceFaultInjector /
    chaos.MeshFaultInjector raise these with ``kind`` in
    {"oom", "device_lost", "slow"}); classified exactly like the real
    XlaRuntimeError equivalents. ``device`` carries the faulting shard's
    device id when the injector models an attributed fault — the same
    information a real per-core XLA error exposes in its message."""

    def __init__(self, kind: str, message: Optional[str] = None,
                 device: Optional[int] = None):
        super().__init__(message or f"simulated device fault: {kind}")
        self.kind = kind
        self.device = device


def classify_device_fault(exc: BaseException) -> Optional[str]:
    """Return the device-fault kind ("oom" | "device_lost" | "slow" |
    "xla") when
    ``exc`` is a device error, None for logic/solver faults. Matches on
    the exception type name (jaxlib's XlaRuntimeError lives at different
    import paths across releases) plus message markers."""
    if isinstance(exc, DeviceFaultError):
        return exc.kind
    if type(exc).__name__ != "XlaRuntimeError":
        return None
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _LOST_MARKERS):
        return "device_lost"
    if any(m in msg for m in _SLOW_MARKERS):
        return "slow"
    return "xla"


def attribute_device_fault(exc: BaseException,
                           devices: Optional[Iterable[int]] = None
                           ) -> Optional[int]:
    """Which device does a classified fault name? The injected ``device``
    attribute when present, else the first device ordinal the message
    exposes (``_DEVICE_ID_PATTERNS``). Returns None when the error names
    no shard — the SUSPECT-all path — or names one outside ``devices``
    (a stale ordinal from a previous mesh must not quarantine a device
    that was not even solving)."""
    dev = getattr(exc, "device", None)
    if dev is None:
        msg = str(exc)
        for pat in _DEVICE_ID_PATTERNS:
            m = pat.search(msg)
            if m:
                dev = int(m.group(1))
                break
    if dev is None:
        return None
    dev = int(dev)
    if devices is not None and dev not in set(devices):
        return None
    return dev


class _DeviceRecord:
    """One device's health state. ``state`` is "ok" | "suspect" |
    "quarantined"; PROBE is derived (quarantined with an expired
    window) so virtual-clock advances need no transition callback."""

    __slots__ = ("state", "consecutive_faults", "total_faults",
                 "last_kind", "quarantined_until", "readmissions")

    def __init__(self):
        self.state = "ok"
        self.consecutive_faults = 0
        self.total_faults = 0
        self.last_kind: Optional[str] = None
        self.quarantined_until: Optional[float] = None
        self.readmissions = 0


class DeviceHealth:
    """Per-device health lattice + fleet cool-down state machine
    (module-global ``DEVICE_HEALTH`` instance; allocate consults it
    every cycle). The pre-lattice single-device API (``record_fault``
    with no device, ``record_ok``, ``available``, ``cooldown_remaining``)
    operates on the FLEET window — the D=1 degenerate case — so existing
    callers and tests are unchanged."""

    def __init__(self, cooldown_s: float = DEFAULT_COOLDOWN_S,
                 max_cooldown_s: float = DEFAULT_MAX_COOLDOWN_S,
                 time_fn=time.monotonic):
        self.cooldown_s = cooldown_s
        self.max_cooldown_s = max_cooldown_s
        self.time_fn = time_fn
        self._lock = threading.Lock()
        self.consecutive_faults = 0
        self.total_faults = 0
        self.last_kind: Optional[str] = None
        self._cooldown_until: Optional[float] = None
        self._devices: Dict[int, _DeviceRecord] = {}

    # -- fleet-level machine (the original API; D=1 degenerate case) ------

    def record_fault(self, kind: str,
                     device: Optional[int] = None) -> float:
        """A device fault occurred. With ``device`` the fault is
        ATTRIBUTED: quarantine exactly that shard (``quarantine``) and
        leave the fleet window closed — the mesh heals around it.
        Without, the original fleet semantics: open (or, after an
        expired window's failed probe, DOUBLE) the cool-down window and
        mark every known device SUSPECT. A fleet fault reported while
        the window is still open is the same outage classified twice
        (e.g. the tensor refresh AND the solve both blow up in one
        cycle) — it updates ``last_kind`` but neither bumps the counters
        nor extends the window. Returns the window length in force. Also
        publishes ``volcano_device_faults_total{kind}`` for fresh
        faults, so call sites cannot double-count either."""
        if device is not None:
            return self.quarantine(device, kind)
        with self._lock:
            now = self.time_fn()
            if self._cooldown_until is not None \
                    and now < self._cooldown_until:
                self.last_kind = kind
                return self._cooldown_until - now
            self.consecutive_faults += 1
            self.total_faults += 1
            self.last_kind = kind
            window = min(
                self.cooldown_s * (2 ** (self.consecutive_faults - 1)),
                self.max_cooldown_s)
            self._cooldown_until = now + window
            # unattributed: the outage could be any shard — suspect all
            for rec in self._devices.values():
                if rec.state == "ok":
                    rec.state = "suspect"
                rec.last_kind = kind
        from . import metrics
        metrics.register_device_fault(kind)
        self._publish()
        return window

    def record_ok(self, device: Optional[int] = None) -> None:
        """A device solve completed: close the fleet machine back to OK
        and clear SUSPECT marks (the whole healthy mesh just proved
        itself). Quarantined devices stay quarantined — only a probe
        readmits. With ``device``, clears that one device's suspicion.
        No-op when already OK — the hot path stays branch-cheap."""
        with self._lock:
            if device is not None:
                rec = self._devices.get(device)
                if rec is not None and rec.state == "suspect":
                    rec.state = "ok"
                return
            suspects = [r for r in self._devices.values()
                        if r.state == "suspect"]
            if self.consecutive_faults == 0 \
                    and self._cooldown_until is None and not suspects:
                return
            self.consecutive_faults = 0
            self._cooldown_until = None
            for rec in suspects:
                rec.state = "ok"
        self._publish()

    def available(self) -> bool:
        """May allocate dispatch to the device fleet this cycle? True in
        OK and PROBE (window expired — one re-probe attempt is the only
        way to learn the device recovered), False inside the window."""
        with self._lock:
            until = self._cooldown_until
            return until is None or self.time_fn() >= until

    def cooldown_remaining(self) -> float:
        with self._lock:
            if self._cooldown_until is None:
                return 0.0
            return max(0.0, self._cooldown_until - self.time_fn())

    # -- per-device lattice ----------------------------------------------

    def quarantine(self, device: int, kind: str) -> float:
        """An ATTRIBUTED fault: pull ``device`` out of the mesh. Same
        dedup/doubling contract as the fleet window, keyed per device: a
        fault inside the open window only updates ``last_kind``; a fresh
        one (first, or a failed probe after expiry) doubles the window
        (capped). The caller owns the epoch bump — a quarantine changes
        the device set, so the resident tensor layout is stale (vlint
        VT021). Returns the window length in force."""
        with self._lock:
            rec = self._devices.setdefault(int(device), _DeviceRecord())
            now = self.time_fn()
            if rec.quarantined_until is not None \
                    and now < rec.quarantined_until:
                rec.last_kind = kind
                return rec.quarantined_until - now
            rec.consecutive_faults += 1
            rec.total_faults += 1
            rec.last_kind = kind
            rec.state = "quarantined"
            window = min(
                self.cooldown_s * (2 ** (rec.consecutive_faults - 1)),
                self.max_cooldown_s)
            rec.quarantined_until = now + window
        from . import metrics
        metrics.register_device_fault(kind)
        metrics.register_device_quarantine(kind)
        self._publish()
        return window

    def readmit(self, device: int) -> None:
        """A quarantined device's PROBE dry-run succeeded: back to OK,
        counters reset. The caller owns the epoch bump — readmission
        grows the device set, re-forming the mesh (vlint VT021)."""
        with self._lock:
            rec = self._devices.get(int(device))
            if rec is None or rec.state != "quarantined":
                return
            rec.state = "ok"
            rec.consecutive_faults = 0
            rec.quarantined_until = None
            rec.readmissions += 1
        from . import metrics
        metrics.register_device_readmission()
        self._publish()

    def healthy_devices(self, device_ids: Iterable[int]) -> List[int]:
        """The subset of ``device_ids`` eligible for LIVE solves, in the
        given order: everything not quarantined. SUSPECT devices stay in
        (suspicion without attribution must not shrink the mesh); PROBE
        devices stay out — an expired window readmits only through a
        successful dry-run, never a live decision. Also registers
        previously unseen ids so unattributed faults can suspect them."""
        with self._lock:
            out = []
            for did in device_ids:
                rec = self._devices.setdefault(int(did), _DeviceRecord())
                if rec.state != "quarantined":
                    out.append(did)
            return out

    def probe_candidates(self, device_ids: Iterable[int]) -> List[int]:
        """Quarantined devices whose window expired — the PROBE state:
        ready for a throwaway dry-run solve (allocate owns the probe;
        success readmits, a fault doubles the window)."""
        with self._lock:
            now = self.time_fn()
            return [did for did in device_ids
                    if (rec := self._devices.get(int(did))) is not None
                    and rec.state == "quarantined"
                    and rec.quarantined_until is not None
                    and now >= rec.quarantined_until]

    def device_state(self, device: int) -> str:
        """"ok" | "suspect" | "quarantined" | "probe" (derived)."""
        with self._lock:
            rec = self._devices.get(int(device))
            if rec is None:
                return "ok"
            if rec.state == "quarantined":
                if rec.quarantined_until is not None \
                        and self.time_fn() >= rec.quarantined_until:
                    return "probe"
                return "quarantined"
            return rec.state

    # -- introspection / lifecycle ---------------------------------------

    def detail(self) -> dict:
        with self._lock:
            until = self._cooldown_until
            now = self.time_fn()
            devices = {}
            healthy = quarantined = 0
            for did in sorted(self._devices):
                rec = self._devices[did]
                if rec.state == "quarantined":
                    quarantined += 1
                    state = ("probe" if rec.quarantined_until is not None
                             and now >= rec.quarantined_until
                             else "quarantined")
                    remaining = round(max(
                        0.0, (rec.quarantined_until or now) - now), 3)
                else:
                    healthy += 1
                    state = rec.state
                    remaining = 0.0
                devices[str(did)] = {
                    "state": state,
                    "consecutive_faults": rec.consecutive_faults,
                    "total_faults": rec.total_faults,
                    "last_kind": rec.last_kind,
                    "window_remaining_s": remaining,
                    "readmissions": rec.readmissions,
                }
            return {
                "available": until is None or now >= until,
                "consecutive_faults": self.consecutive_faults,
                "total_faults": self.total_faults,
                "last_kind": self.last_kind,
                "cooldown_remaining_s": round(max(0.0, (until - now)), 3)
                if until is not None else 0.0,
                "devices": devices,
                "devices_known": len(devices),
                "devices_healthy": healthy,
                "devices_quarantined": quarantined,
            }

    def reset(self, time_fn=None) -> None:
        """Full reset, fleet AND per-device lattice (tests / sim
        restart — health lives in process memory, so a simulated process
        death forgets quarantines exactly like a real one); optionally
        swap the time source."""
        with self._lock:
            self.consecutive_faults = 0
            self.total_faults = 0
            self.last_kind = None
            self._cooldown_until = None
            self._devices = {}
            if time_fn is not None:
                self.time_fn = time_fn
        self._publish()

    def _publish(self) -> None:
        from . import metrics
        d = self.detail()
        metrics.set_device_health(d["available"], d)
        metrics.set_mesh_devices_healthy(d["devices_healthy"],
                                         d["devices_known"])


DEVICE_HEALTH = DeviceHealth()
