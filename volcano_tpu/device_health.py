"""Device-fault containment: classify accelerator errors and cool down.

A batched solve can fail for two very different reasons, and the right
response differs (docs/robustness.md):

- **Solver logic faults** (non-finite scores, garbage indices, shape
  bugs — ``actions.allocate.SolverFault``): the device is fine, the
  program is wrong. Falling back to the sequential placer and retrying
  the device engine next cycle is correct.
- **Device faults** (XLA ``RESOURCE_EXHAUSTED`` OOM, device-lost,
  backend-internal errors): retrying the device engine immediately just
  re-fails — and after a device loss the device-resident tensor mirrors
  are gone, so any cached device state is poison.

``classify_device_fault`` tells the two apart; ``DeviceHealth`` is the
cool-down state machine the allocate action consults:

    OK --fault--> COOLDOWN (allocate degrades to the CPU/callbacks
                  engine; volcano_device_healthy=0)
    COOLDOWN --window expires--> PROBE (the next cycle attempts the
                  device engine once)
    PROBE --success--> OK (counters reset)
    PROBE --fault--> COOLDOWN, window doubled (capped)

Every transition is exported (``volcano_device_faults_total{kind}``,
``volcano_device_healthy``, /healthz?detail). The window runs on an
injectable ``time_fn`` so the sim and tests drive it on virtual time.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

DEFAULT_COOLDOWN_S = 30.0
DEFAULT_MAX_COOLDOWN_S = 480.0

# substrings that mark an XLA runtime error as a DEVICE fault rather
# than a program bug (jaxlib surfaces both through XlaRuntimeError)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "OOM")
_LOST_MARKERS = ("DEVICE_LOST", "device lost", "Device lost",
                 "DATA_LOSS", "failed to enqueue")


class DeviceFaultError(RuntimeError):
    """A simulated device error (chaos.DeviceFaultInjector raises these
    with ``kind`` in {"oom", "device_lost"}); classified exactly like
    the real XlaRuntimeError equivalents."""

    def __init__(self, kind: str, message: Optional[str] = None):
        super().__init__(message or f"simulated device fault: {kind}")
        self.kind = kind


def classify_device_fault(exc: BaseException) -> Optional[str]:
    """Return the device-fault kind ("oom" | "device_lost" | "xla") when
    ``exc`` is a device error, None for logic/solver faults. Matches on
    the exception type name (jaxlib's XlaRuntimeError lives at different
    import paths across releases) plus message markers."""
    if isinstance(exc, DeviceFaultError):
        return exc.kind
    if type(exc).__name__ != "XlaRuntimeError":
        return None
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _LOST_MARKERS):
        return "device_lost"
    return "xla"


class DeviceHealth:
    """Cool-down state machine for the device engines (module-global
    ``DEVICE_HEALTH`` instance; allocate consults it every cycle)."""

    def __init__(self, cooldown_s: float = DEFAULT_COOLDOWN_S,
                 max_cooldown_s: float = DEFAULT_MAX_COOLDOWN_S,
                 time_fn=time.monotonic):
        self.cooldown_s = cooldown_s
        self.max_cooldown_s = max_cooldown_s
        self.time_fn = time_fn
        self._lock = threading.Lock()
        self.consecutive_faults = 0
        self.total_faults = 0
        self.last_kind: Optional[str] = None
        self._cooldown_until: Optional[float] = None

    def record_fault(self, kind: str) -> float:
        """A device fault occurred: open (or, after an expired window's
        failed probe, DOUBLE) the cool-down window. A fault reported
        while the window is still open is the same outage classified
        twice (e.g. the tensor refresh AND the solve both blow up in one
        cycle) — it updates ``last_kind`` but neither bumps the counters
        nor extends the window. Returns the window length in force. Also
        publishes ``volcano_device_faults_total{kind}`` for fresh
        faults, so call sites cannot double-count either."""
        with self._lock:
            now = self.time_fn()
            if self._cooldown_until is not None \
                    and now < self._cooldown_until:
                self.last_kind = kind
                return self._cooldown_until - now
            self.consecutive_faults += 1
            self.total_faults += 1
            self.last_kind = kind
            window = min(
                self.cooldown_s * (2 ** (self.consecutive_faults - 1)),
                self.max_cooldown_s)
            self._cooldown_until = now + window
        from . import metrics
        metrics.register_device_fault(kind)
        self._publish()
        return window

    def record_ok(self) -> None:
        """A device solve completed: close the state machine back to OK
        (no-op when already OK — the hot path stays branch-cheap)."""
        with self._lock:
            if self.consecutive_faults == 0 \
                    and self._cooldown_until is None:
                return
            self.consecutive_faults = 0
            self._cooldown_until = None
        self._publish()

    def available(self) -> bool:
        """May allocate dispatch to the device this cycle? True in OK
        and PROBE (window expired — one re-probe attempt is the only way
        to learn the device recovered), False inside the window."""
        with self._lock:
            until = self._cooldown_until
            return until is None or self.time_fn() >= until

    def cooldown_remaining(self) -> float:
        with self._lock:
            if self._cooldown_until is None:
                return 0.0
            return max(0.0, self._cooldown_until - self.time_fn())

    def detail(self) -> dict:
        with self._lock:
            until = self._cooldown_until
            now = self.time_fn()
            return {
                "available": until is None or now >= until,
                "consecutive_faults": self.consecutive_faults,
                "total_faults": self.total_faults,
                "last_kind": self.last_kind,
                "cooldown_remaining_s": round(max(0.0, (until - now)), 3)
                if until is not None else 0.0,
            }

    def reset(self, time_fn=None) -> None:
        """Full reset (tests / sim restart); optionally swap the time
        source."""
        with self._lock:
            self.consecutive_faults = 0
            self.total_faults = 0
            self.last_kind = None
            self._cooldown_until = None
            if time_fn is not None:
                self.time_fn = time_fn
        self._publish()

    def _publish(self) -> None:
        from . import metrics
        d = self.detail()
        metrics.set_device_health(d["available"], d)


DEVICE_HEALTH = DeviceHealth()
